"""Built-in verifier checks + the check registry.

Each check is a generator ``fn(ctx) -> Iterable[Diagnostic]`` registered
under a stable id, mirroring ``analysis.register_pass``; custom checks
register the same way::

    from paddle_tpu.static_analysis import register_check, Diagnostic, Severity

    @register_check("no-print-ops")
    def no_print_ops(ctx):
        for block_idx, op_idx, op in ctx.graph.order:
            if op.type == "print":
                yield ctx.diag("no-print-ops", Severity.WARNING,
                               "print op in production program",
                               block_idx=block_idx, op_idx=op_idx, op=op)

The catalog (see README "Static analysis / lint"):

==========================  ========  ====================================
check id                    severity  violation
==========================  ========  ====================================
use-before-def              ERROR     non-persistable var read before any
                                      write (or never declared at all)
double-write                ERROR/W   second blind write to a var (ERROR
                                      for persistables: donated-buffer
                                      aliasing hazard; WARNING otherwise)
shape-dtype-drift           ERROR/W   re-inferred output dtype (ERROR) or
                                      static shape (WARNING) disagrees
                                      with recorded Variable metadata
orphaned-fetch              ERROR     fetch target neither produced, fed,
                                      nor persistable (or missing wholly)
sub-block-index             ERROR     attrs["sub_block"] out of range or
                                      self-referential
collective-ring             ERROR/W   collective op missing ring_id or
                                      send_v2/recv_v2 missing peer
                                      (ERROR); c_gen_nccl_id without a
                                      matching c_comm_init (WARNING)
unreferenced-op             INFO      op output never read / fetched —
                                      advisory twin of DCE
resilience-finite-guard     INFO      training program fetches its loss
                                      but no NaN/Inf step-guard is
                                      enabled (PADDLE_TPU_NAN_GUARD /
                                      program._nan_guard)
peak-memory-over-budget     ERROR     liveness peak-memory estimate
                                      exceeds the configured HBM budget
                                      (PADDLE_TPU_HBM_BUDGET /
                                      program._hbm_budget)
collective-schedule-        ERROR     cross-worker collective schedules
divergence                            diverge (kind/dtype/numel/order,
                                      or mispaired p2p) — runs when the
                                      per-worker programs are supplied
degenerate-sharding         WARNING   var marked sharded over parts the
                                      tensor dim cannot fill (or fill
                                      evenly) — silently degenerate
                                      distribution
oversized-replicated-       WARNING   replicated persistable larger
persistable                           than the replication budget on a
                                      multi-worker program — shard it
executor-host-sync-in-loop  INFO/E    host-IO op (save/load/...) in
                                      the hot loop — a while/recurrent
                                      body, or the per-step program of
                                      a training run — forces a device
                                      sync every iteration and defeats
                                      async dispatch overlap (ERROR
                                      under PADDLE_TPU_STRICT_SYNC=1
                                      or in the serving hot loop)
race-inflight-write         ERROR     persistable fetched AND written,
                                      or a fed data var overwritten —
                                      overlapping in-flight steps race
                                      on the buffer (silent when
                                      max_in_flight<=1; see
                                      static_analysis.concurrency)
donated-buffer-live-read    ERROR     pending FetchHandle aliases a
                                      buffer an in-place op (fused
                                      optimizer, in-place collective)
                                      donates in the next in-flight
                                      step
scope-overlap               ERROR     coresident programs' scope
                                      footprints are not disjoint —
                                      multi-tenant isolation proof
                                      fails (runs when coresident
                                      programs are supplied)
sync-in-hot-loop            ERROR     zero-sync certificate violation:
                                      host-sync point (host-IO, host
                                      table, eager while probe) in the
                                      steady-state loop (runs when
                                      certifying or strict)
fused-op-missing-grad       ERROR     fused op registered no_grad=True
                                      on a parameter-derived path of a
                                      training program — its param
                                      grads would silently zero
fusible-pattern-not-fused   INFO      pattern the fusion pipeline
                                      matched but will not rewrite,
                                      with the cost-model reason
quantizable-bucket-not-     INFO      ICI-bound gradient bucket the
quantized                             cost model prices as an int8
                                      quantization win but that runs
                                      bf16 (no plan mark / env
                                      threshold, kill switch, or
                                      uncalibrated autotune family)
collective-crosses-slow-    INFO      ring-0 gradient exchange whose
tier                                  ring spans slices carrying >=
                                      threshold bytes flat across the
                                      DCN tier (rewrite disabled, plan
                                      mark pins flat, asymmetric
                                      topology, or no topology in
                                      ClusterSpec), with the priced
                                      per-tier delta in the hint
collective-start-without-   ERROR     c_allreduce_start with no
wait                                  matching c_allreduce_wait after
                                      it — the in-flight reduction is
                                      never barriered
wait-without-start          ERROR     c_allreduce_wait with no
                                      c_allreduce_start before it —
                                      barriers a reduction nobody
                                      launched
double-wait                 ERROR     duplicate c_allreduce_wait for
                                      one overlap bucket
overlap-opportunity-        INFO      bucketed collective kept fused
unexploited                           synchronous despite a window of
                                      dead compute (overlap disabled,
                                      proof-reverted, or no-window)
decode-shape-unbucketed     WARNING   while body concatenates a loop
                                      carry with per-step data and
                                      writes it back — operand shapes
                                      grow with the loop index, so
                                      every decode step is a fresh
                                      shape bucket (use the ring-buffer
                                      KV cache: layers.decode_loop)
==========================  ========  ====================================
"""

from .defuse import (SUB_BLOCK_DESCENT_OPS, _machinery_defined_names,
                     resolve_sub_block, sub_block_reads_recursive)
from .diagnostics import Diagnostic, Severity
from ..ops.registry import EMPTY_VAR_NAME

__all__ = ["register_check", "get_check", "all_checks", "VerifyContext"]

_CHECKS = {}


def register_check(check_id):
    """Register ``fn(ctx) -> Iterable[Diagnostic]`` under ``check_id``
    (the ``register_pass`` idiom; later registration replaces earlier,
    so a project can override a built-in)."""

    def deco(fn):
        _CHECKS[check_id] = fn
        return fn

    return deco


def get_check(check_id):
    return _CHECKS[check_id]


def all_checks():
    """Ordered {id: fn} of registered checks."""
    return dict(_CHECKS)


class VerifyContext:
    """What a check sees: the program, the def-use graph, the (optional)
    fetch targets, the (optional) per-worker program list for the
    cross-worker checks, and a Diagnostic factory that fills in
    coordinates.  ``interp``/``cost`` are computed lazily so the cheap
    structural checks never pay for the analyzer."""

    def __init__(self, program, graph, targets=None, workers=None,
                 analysis=None, worker_schedules=None,
                 max_in_flight=None, coresident=None,
                 certify_zero_sync=False):
        self.program = program
        self.graph = graph
        self.targets = tuple(targets or ())
        self.workers = list(workers) if workers else None
        # precomputed per-worker schedules (Program.analyze already
        # extracted them) so the divergence check doesn't re-interpret
        # every worker program
        self.worker_schedules = worker_schedules
        self._interp, self._cost = analysis or (None, None)
        # concurrency context (ISSUE 10): the in-flight depth the race
        # checks assume (None → program mark / env / 1), programs
        # sharing this one's Executor scope, and whether the zero-sync
        # certificate check should run unconditionally
        self.max_in_flight = max_in_flight
        self.coresident = list(coresident) if coresident else None
        self.certify_zero_sync = bool(certify_zero_sync)

    @property
    def interp(self):
        if self._interp is None:
            from .interp import interpret_program

            self._interp = interpret_program(
                self.program,
                nranks=len(self.workers) if self.workers else None)
        return self._interp

    @property
    def cost(self):
        if self._cost is None:
            from .cost import estimate_cost

            self._cost = estimate_cost(
                self.program, interp=self.interp, targets=self.targets)
        return self._cost

    def var(self, name, near_block=None):
        """Recursive var lookup starting at ``near_block`` (a block idx)."""
        b = (self.program.block(near_block) if near_block is not None
             else self.program.global_block())
        return b._find_var_recursive(name)

    def diag(self, check, severity, message, block_idx=None, op_idx=None,
             op=None, var_names=(), hint=""):
        return Diagnostic(
            check, severity, message,
            block_idx=block_idx, op_idx=op_idx,
            op_type=op.type if op is not None else None,
            op_id=op.attrs.get("__op_id__") if op is not None else None,
            var_names=var_names, hint=hint,
        )


def _is_defined_root(ctx, name, block_idx):
    """Names with a value before any op runs: persistables (scope-resident
    across runs) and data vars (fed)."""
    v = ctx.var(name, block_idx)
    if v is None:
        return False
    return bool(v.persistable or v.is_data)


# ---------------------------------------------------------------------------
# use-before-def
# ---------------------------------------------------------------------------

@register_check("use-before-def")
def check_use_before_def(ctx):
    """Walk in execution order threading the defined-name set through
    sub-block descent; flag reads of non-persistable, non-fed vars with no
    prior write (the dangling edges a broken fuse/DCE pass leaves)."""
    program = ctx.program
    reported = set()
    visited_blocks = set()

    def walk(block, defined):
        if block.idx in visited_blocks:
            # sub_block cycle in a malformed program: sub-block-index
            # reports it; don't recurse forever here
            return
        visited_blocks.add(block.idx)
        for op_idx, op in enumerate(block.ops):
            for n in op.input_arg_names:
                if (not n or n == EMPTY_VAR_NAME or n in defined
                        or n in reported):
                    continue
                if _is_defined_root(ctx, n, block.idx):
                    defined.add(n)
                    continue
                reported.add(n)
                v = ctx.var(n, block.idx)
                if v is None:
                    msg = ("op reads %r which is not declared in any "
                           "reachable block" % n)
                    hint = ("a pass rewired an input to a var it never "
                            "created — create the var or fix the slot")
                else:
                    msg = ("op reads %r before any op writes it (and it "
                           "is neither persistable nor fed)" % n)
                    hint = ("reorder the producer before this op, or mark "
                            "the var persistable/is_data if it is "
                            "scope-provided")
                yield ctx.diag(
                    "use-before-def", Severity.ERROR, msg,
                    block_idx=block.idx, op_idx=op_idx, op=op,
                    var_names=(n,), hint=hint)
            if op.type in SUB_BLOCK_DESCENT_OPS:
                inner = resolve_sub_block(program, op,
                                          host_block_idx=block.idx)
                if inner is not None:
                    inner_defined = set(defined)
                    inner_defined.update(_machinery_defined_names(op))
                    yield from walk(inner, inner_defined)
            for n in op.output_arg_names:
                if n and n != EMPTY_VAR_NAME:
                    defined.add(n)

    yield from walk(program.global_block(), set())


# ---------------------------------------------------------------------------
# double-write
# ---------------------------------------------------------------------------

@register_check("double-write")
def check_double_write(ctx):
    """Two writes to one var in a block with no read in between, where the
    second writer does not read-modify-write it: the first write is dead,
    and for persistables it aliases the jit cache's donated param buffers
    (executor.py donates the mutated-param argument — two blind writes in
    one step mean one update silently vanishes).

    Read-modify-write ops (sgd ParamOut==Param, batch_norm MeanOut==Mean,
    c_allreduce in-place) and control-flow merges (conditional branches
    each assign the merge var; the op semantically reads the prior value)
    are not violations.
    """
    for block in ctx.program.blocks:
        if block.idx not in ctx.graph.walked_blocks:
            continue
        last_write = {}   # name -> (op_idx, op)
        read_since = {}   # name -> True once read after last write
        for op_idx, op in enumerate(block.ops):
            for n in op.input_arg_names:
                read_since[n] = True
            sub = resolve_sub_block(ctx.program, op,
                                    host_block_idx=block.idx)
            if sub is not None:
                # closure reads never appear on the op's input slots
                for n in sub_block_reads_recursive(ctx.program, sub):
                    read_since[n] = True
            is_cf = op.type in SUB_BLOCK_DESCENT_OPS
            if is_cf:
                # the sub-block body reads/merges the carried names
                for n in op.output_arg_names:
                    read_since[n] = True
            for n in op.output_arg_names:
                if not n or n == EMPTY_VAR_NAME:
                    continue
                prev = last_write.get(n)
                # read-modify-write ops (sgd, batch_norm stats, in-place
                # allreduce) are exempt via read_since: their own input
                # read was recorded just above
                if prev is not None and not read_since.get(n) and not is_cf:
                    v = ctx.var(n, block.idx)
                    persistable = bool(v is not None and v.persistable)
                    sev = Severity.ERROR if persistable else Severity.WARNING
                    what = ("persistable %r (donation-aliasing hazard: the "
                            "first update is lost in the donated buffer)"
                            if persistable else
                            "%r (the first write is dead)")
                    yield ctx.diag(
                        "double-write", sev,
                        ("op overwrites " + what + "; prior write at op %d "
                         "(%s) was never read") % (n, prev[0], prev[1].type),
                        block_idx=block.idx, op_idx=op_idx, op=op,
                        var_names=(n,),
                        hint="drop the dead writer or rename one output")
                last_write[n] = (op_idx, op)
                read_since[n] = False


# ---------------------------------------------------------------------------
# shape/dtype re-inference drift
# ---------------------------------------------------------------------------

def _shapes_conflict(recorded, inferred):
    """Static-dim conflict only: -1/None dims are unknown, and rank-1 vs
    rank-0 scalars round-trip loosely through serialization, so only
    same-rank tensors with differing static dims count."""
    if recorded is None or inferred is None:
        return False
    if len(recorded) != len(inferred):
        return not (len(recorded) == 0 or len(inferred) == 0)
    for r, i in zip(recorded, inferred):
        if r is None or i is None or r < 0 or i < 0:
            continue
        if int(r) != int(i):
            return True
    return False


@register_check("shape-dtype-drift")
def check_shape_dtype_drift(ctx):
    """Re-run the jax.eval_shape inference engine (framework.py's
    append-time InferShape) over every op and diff against the recorded
    Variable metadata.  At build time the two agree by construction, so a
    disagreement means a pass rewired the graph without re-inferring —
    dtype drift is an ERROR (it changes numerics/casts silently), static
    shape drift a WARNING (execution re-traces with concrete feeds)."""
    from ..ops import registry

    for block_idx, op_idx, op in ctx.graph.order:
        if op.type.endswith("_grad") or op.type in ("feed", "fetch"):
            continue
        block = ctx.program.block(block_idx)
        try:
            inferred = registry.infer_output_structs(op, block)
        except registry.OpNotRegistered:
            continue
        except Exception as e:
            # at build time append_op would have propagated this, so a
            # raise here means a rewrite left metadata the lowering
            # rejects outright — the strongest drift signal there is
            yield ctx.diag(
                "shape-dtype-drift", Severity.ERROR,
                "the op's lowering rejects the recorded input metadata "
                "(%s: %s)" % (type(e).__name__, str(e)[:200]),
                block_idx=block_idx, op_idx=op_idx, op=op,
                var_names=tuple(op.input_arg_names),
                hint="a pass rewired this op's inputs to incompatible "
                     "vars — fix the rewrite or re-infer shapes")
            continue
        if not inferred:
            continue
        for n, (shape, dtype) in inferred.items():
            var = block._find_var_recursive(n)
            if var is None:
                continue
            recorded_dtype = var.dtype
            if recorded_dtype is not None and dtype != str(recorded_dtype):
                yield ctx.diag(
                    "shape-dtype-drift", Severity.ERROR,
                    "recorded dtype of %r is %s but the op's lowering "
                    "produces %s" % (n, recorded_dtype, dtype),
                    block_idx=block_idx, op_idx=op_idx, op=op,
                    var_names=(n,),
                    hint="re-run shape inference after rewriting, or cast "
                         "explicitly")
            elif _shapes_conflict(var.shape, shape):
                yield ctx.diag(
                    "shape-dtype-drift", Severity.WARNING,
                    "recorded shape of %r is %s but the op's lowering "
                    "produces %s" % (n, tuple(var.shape), tuple(shape)),
                    block_idx=block_idx, op_idx=op_idx, op=op,
                    var_names=(n,),
                    hint="update the var's shape metadata after rewriting")


# ---------------------------------------------------------------------------
# orphaned fetch targets
# ---------------------------------------------------------------------------

@register_check("orphaned-fetch")
def check_orphaned_fetch(ctx):
    """Every fetch target (explicit ``targets`` plus any fetch op's inputs)
    must be produced by a surviving op, fed, or persistable — the exact
    invariant a too-eager rewrite pass breaks."""
    wanted = list(ctx.targets)
    for block_idx, op_idx, op in ctx.graph.order:
        if op.type == "fetch":
            wanted.extend(op.input_arg_names)
    seen = set()
    for n in wanted:
        if not n or n == EMPTY_VAR_NAME or n in seen:
            continue
        seen.add(n)
        v = ctx.var(n)
        if v is None:
            yield ctx.diag(
                "orphaned-fetch", Severity.ERROR,
                "fetch target %r does not exist in the program" % n,
                var_names=(n,),
                hint="a pass pruned the target var — exclude fetch "
                     "targets from rewrites (pass targets= to the "
                     "Analyzer)")
        elif not (ctx.graph.is_produced(n) or v.persistable or v.is_data):
            yield ctx.diag(
                "orphaned-fetch", Severity.ERROR,
                "fetch target %r is never produced by any op (nor fed, "
                "nor persistable)" % n,
                var_names=(n,),
                hint="the producing op was fused/eliminated — rerun the "
                     "pass with targets= or keep the producer")


# ---------------------------------------------------------------------------
# sub-block indices
# ---------------------------------------------------------------------------

@register_check("sub-block-index")
def check_sub_block_index(ctx):
    for block in ctx.program.blocks:
        for op_idx, op in enumerate(block.ops):
            if "sub_block" not in op.attrs:
                continue
            idx = op.attrs["sub_block"]
            if (not isinstance(idx, int)
                    or idx < 0 or idx >= ctx.program.num_blocks):
                yield ctx.diag(
                    "sub-block-index", Severity.ERROR,
                    "attrs['sub_block']=%r is not a valid block index "
                    "(program has %d blocks)" % (idx, ctx.program.num_blocks),
                    block_idx=block.idx, op_idx=op_idx, op=op,
                    hint="clone/serialize must remap sub_block indices")
            elif idx == block.idx:
                yield ctx.diag(
                    "sub-block-index", Severity.ERROR,
                    "op's sub_block is its own block (infinite descent)",
                    block_idx=block.idx, op_idx=op_idx, op=op)


# ---------------------------------------------------------------------------
# collective ring-id pairing (transpiled programs)
# ---------------------------------------------------------------------------

# c_sync_*_stream ops are ring-less by design and match none of these
_COLLECTIVE_OP_PREFIXES = ("c_allreduce", "c_reduce", "c_broadcast",
                           "c_allgather", "c_reducescatter", "c_scatter")
# collectives emitted by the parallel program emitters (moe/ulysses
# all_to_all resharding, ring-attention/pipeline ppermute hops) — no
# ``c_`` prefix but the same ring_id contract
_RINGED_OP_TYPES = ("all_to_all", "ppermute")


@register_check("collective-ring")
def check_collective_ring(ctx):
    """Transpiled programs: every collective must carry an integer
    ``ring_id`` — the transpiler-emitted ``c_*`` families AND the
    collectives the parallel emitters insert (``all_to_all`` from
    parallel/{moe,ulysses}.py, ``ppermute`` from
    parallel/ring_attention.py); bootstrap pairs (``c_gen_nccl_id`` →
    ``c_comm_init``) must agree per ring, every ring a collective uses
    should have a bootstrap pair when any bootstrap exists, and p2p
    send/recv ops must name an integer ``peer`` (reference keeps rings
    consistent in C++; here a mismatch would silently place collectives
    on different meshes).  Note: a single rank's program legitimately
    has asymmetric send/recv peers (pipeline stages), so pairing is
    checked per-op, not globally."""
    gen_rings = {}
    init_rings = set()
    used_rings = {}
    for block_idx, op_idx, op in ctx.graph.order:
        t = op.type
        if t == "c_gen_nccl_id":
            gen_rings[op.attrs.get("ring_id", 0)] = (block_idx, op_idx, op)
        elif t == "c_comm_init":
            init_rings.add(op.attrs.get("ring_id", 0))
        elif t in ("send_v2", "recv_v2"):
            if not isinstance(op.attrs.get("peer"), int):
                yield ctx.diag(
                    "collective-ring", Severity.ERROR,
                    "%s op has no integer peer attr (got %r)"
                    % (t, op.attrs.get("peer")),
                    block_idx=block_idx, op_idx=op_idx, op=op,
                    hint="p2p ops must name their partner rank")
            used_rings.setdefault(op.attrs.get("ring_id"),
                                  (block_idx, op_idx, op))
        elif t.startswith(_COLLECTIVE_OP_PREFIXES) or t in _RINGED_OP_TYPES:
            ring = op.attrs.get("ring_id")
            if ring is None or not isinstance(ring, int):
                yield ctx.diag(
                    "collective-ring", Severity.ERROR,
                    "collective op has no integer ring_id attr (got %r)"
                    % (ring,),
                    block_idx=block_idx, op_idx=op_idx, op=op,
                    hint="the transpiler/parallel emitter must stamp "
                         "ring_id on every collective it inserts")
            else:
                used_rings.setdefault(ring, (block_idx, op_idx, op))
    # key=repr: a malformed program may mix int and str ring ids — the
    # check must report them, not die sorting them
    for ring, (block_idx, op_idx, op) in sorted(gen_rings.items(),
                                                key=lambda kv: repr(kv[0])):
        if ring not in init_rings:
            yield ctx.diag(
                "collective-ring", Severity.WARNING,
                "c_gen_nccl_id for ring %r has no matching c_comm_init"
                % (ring,),
                block_idx=block_idx, op_idx=op_idx, op=op,
                hint="append c_comm_init with the same ring_id in the "
                     "startup program")
    # a program that carries its own bootstrap (startup, or merged
    # startup+main) must bootstrap every ring its collectives use; a
    # main-only program (gen_rings empty) is exempt — its bootstrap
    # lives in the separate startup program
    if gen_rings:
        for ring, (block_idx, op_idx, op) in sorted(
                used_rings.items(), key=lambda kv: repr(kv[0])):
            if ring not in gen_rings and ring is not None:
                yield ctx.diag(
                    "collective-ring", Severity.WARNING,
                    "collective uses ring %r but the program only "
                    "bootstraps ring(s) %s"
                    % (ring, sorted(gen_rings, key=repr)),
                    block_idx=block_idx, op_idx=op_idx, op=op,
                    hint="transpiler.collective.ensure_comm_ring "
                         "appends the c_gen_nccl_id/c_comm_init pair")


# ---------------------------------------------------------------------------
# unreferenced ops (advisory DCE twin)
# ---------------------------------------------------------------------------

# op types whose value is their side effect, not a consumed output
_SIDE_EFFECT_OPS = frozenset((
    "feed", "fetch", "print", "save", "load", "save_combine",
    "load_combine", "c_gen_nccl_id", "c_comm_init", "c_sync_calc_stream",
    "c_sync_comm_stream", "barrier",
))


@register_check("unreferenced-op")
def check_unreferenced_op(ctx):
    """Ops in the global block whose outputs nothing reads and nothing
    fetches: dead weight the DCE pass would remove.  Advisory (INFO) —
    intentionally kept side-effecting, persistable-writing and
    control-flow ops are exempt."""
    targets = set(ctx.targets)
    block = ctx.program.global_block()
    for op_idx, op in enumerate(block.ops):
        if (op.type in _SIDE_EFFECT_OPS
                or op.type in SUB_BLOCK_DESCENT_OPS
                or op.type.endswith("_grad")):
            continue
        outs = [n for n in op.output_arg_names
                if n and n != EMPTY_VAR_NAME]
        if not outs:
            continue
        live = False
        for n in outs:
            v = ctx.var(n)
            if (n in targets or ctx.graph.consumers(n)
                    or (v is not None and v.persistable)):
                live = True
                break
        if not live:
            yield ctx.diag(
                "unreferenced-op", Severity.INFO,
                "no op, fetch target or persistable consumes outputs %s"
                % (outs,),
                block_idx=block.idx, op_idx=op_idx, op=op,
                var_names=tuple(outs),
                hint="dead_code_elimination_pass would remove this op")


@register_check("resilience-finite-guard")
def check_resilience_finite_guard(ctx):
    """Training programs run without the NaN/Inf step-guard: one
    non-finite gradient silently corrupts every parameter it touches,
    and the donated-buffer executor cannot roll the step back after the
    fact.  Advisory (INFO) — inference programs and guarded runs are
    exempt; fires only when fetch targets are given (i.e. a run loop is
    actually reading the loss)."""
    if not ctx.targets:
        return
    is_training = any(
        op.type.endswith("_grad") or op.attrs.get("op_role") == "optimize"
        for _, _, op in ctx.graph.order)
    if not is_training:
        return
    from ..resilience.guard import guard_enabled

    if guard_enabled(ctx.program):
        return
    loss = getattr(ctx.program, "_guard_loss_name", None)
    yield ctx.diag(
        "resilience-finite-guard", Severity.INFO,
        "training program fetches %s but no finite step-guard is "
        "enabled — a NaN/Inf step would be applied to the parameters"
        % (("loss %r" % loss) if loss else list(ctx.targets)),
        block_idx=0,
        var_names=(loss,) if loss else tuple(ctx.targets),
        hint="set PADDLE_TPU_NAN_GUARD=1 (or program._nan_guard=True) so "
             "non-finite steps are skipped, counted and warned about")


# loop-body ops: their sub_block re-runs per iteration, so host IO
# inside costs one sync per ITERATION, not per step.  The host-IO op
# roster itself comes from cost.HOST_IO_OP_TYPES (one source of truth,
# derived from the executor's ops/io_ops list; `print` is jitted via
# jax.debug.print and deliberately absent).
_LOOP_BODY_OPS = ("while", "recurrent")


@register_check("executor-host-sync-in-loop")
def check_executor_host_sync_in_loop(ctx):
    """Advisory: host-IO ops in a hot loop serialize async dispatch.

    Two shapes (both INFO — sometimes a per-step save is the point):

    * a host-IO op inside a ``while``/``recurrent`` sub-block (or any
      block nested under one) — every loop iteration would bounce to
      the host;
    * a host-IO op in the global block of a TRAINING program — the
      per-step program IS the hot loop, so each ``Executor.run`` pays a
      full pipeline drain around the jitted step, exactly the per-batch
      sync latency the async fetch-handle path exists to remove.
    """
    from .cost import HOST_IO_OP_TYPES

    program = ctx.program

    def loop_block_idxs():
        """Block indices reachable through a while/recurrent sub_block."""
        seen = set()
        stack = []
        for block in program.blocks:
            for op in block.ops:
                if op.type in _LOOP_BODY_OPS:
                    inner = resolve_sub_block(program, op,
                                              host_block_idx=block.idx)
                    if inner is not None:
                        stack.append(inner)
        while stack:
            b = stack.pop()
            if b.idx in seen:
                continue
            seen.add(b.idx)
            for op in b.ops:
                inner = resolve_sub_block(program, op,
                                          host_block_idx=b.idx)
                if inner is not None:
                    stack.append(inner)
        return seen

    in_loop = loop_block_idxs()
    is_training = any(
        op.type.endswith("_grad") or op.attrs.get("op_role") == "optimize"
        for _, _, op in ctx.graph.order)
    # ISSUE 10 promotion: under PADDLE_TPU_STRICT_SYNC=1 (or once the
    # program has entered the serving hot loop) the advisory is an
    # ERROR backed by the zero-sync certificate — a per-step host sync
    # there is a throughput bug, not a style note
    from .concurrency import strict_sync_enabled

    strict = strict_sync_enabled(ctx.program)
    severity = Severity.ERROR if strict else Severity.INFO
    for block_idx, op_idx, op in ctx.graph.order:
        if op.type not in HOST_IO_OP_TYPES:
            continue
        if block_idx in in_loop:
            yield ctx.diag(
                "executor-host-sync-in-loop", severity,
                "host-IO op %r at block %d op %d inside a "
                "while/recurrent body forces a device sync every loop "
                "iteration — introduced by Executor.run's host-IO "
                "phase (ops.io_ops.run_host_io_block)%s"
                % (op.type, block_idx, op_idx,
                   "; strict-sync mode fails the zero-sync certificate "
                   "on it" if strict else ""),
                block_idx=block_idx, op_idx=op_idx, op=op,
                hint="hoist the IO out of the loop (checkpoint/print at "
                     "step boundaries) so the loop stays one dispatch")
        elif block_idx == 0 and is_training:
            yield ctx.diag(
                "executor-host-sync-in-loop", severity,
                "host-IO op %r at block %d op %d in a training "
                "program's global block forces a per-step host sync "
                "around the jitted step — introduced by Executor.run's "
                "host-IO phase (ops.io_ops.run_host_io_block)%s"
                % (op.type, block_idx, op_idx,
                   "; strict-sync mode fails the zero-sync certificate "
                   "on it" if strict else ""),
                block_idx=block_idx, op_idx=op_idx, op=op,
                hint="run IO from a separate program at "
                     "checkpoint/print_period boundaries; keep the "
                     "per-step program pure so async dispatch "
                     "(return_numpy=False fetch handles, "
                     "DeviceFeedPipeline feeds) can overlap steps")


# ---------------------------------------------------------------------------
# analyzer-backed checks (abstract interpretation + cost model)
# ---------------------------------------------------------------------------

@register_check("peak-memory-over-budget")
def check_peak_memory_over_budget(ctx):
    """The liveness-based peak-memory estimate must fit the configured
    HBM budget (``PADDLE_TPU_HBM_BUDGET`` / ``program._hbm_budget``, or
    an explicit ``analyze(hbm_budget=...)`` override riding on the
    precomputed cost report).  Skipped when no budget is configured —
    there is nothing to gate against, and guessing a device would make
    CI flaky."""
    from .cost import hbm_budget

    # cheap pre-probe: only build the cost report when some budget
    # source exists (the lazy ctx.cost resolves the same sources)
    if hbm_budget(ctx.program) is None and ctx._cost is None:
        return
    cost = ctx.cost
    budget = cost.hbm_budget
    if budget is None:
        return
    if cost.peak_memory_bytes > budget:
        yield ctx.diag(
            "peak-memory-over-budget", Severity.ERROR,
            "estimated peak memory %d bytes exceeds the HBM budget %d "
            "(persistables %d, peak live activations %d; batch=%d)"
            % (cost.peak_memory_bytes, budget, cost.persistent_bytes,
               cost.peak_memory_bytes - cost.persistent_bytes,
               cost.batch_size),
            block_idx=0,
            hint="shard the largest persistables, enable recompute, or "
                 "cut the assumed batch (PADDLE_TPU_ANALYZE_BATCH)")


@register_check("collective-schedule-divergence")
def check_collective_schedule_divergence(ctx):
    """Cross-worker proof: the N per-worker programs must issue the same
    ordered collectives per ring and pairwise-matched p2p — the static
    deadlock-freedom obligation (see static_analysis/distributed.py).
    Runs only when the worker program set is supplied
    (``verify_program(..., workers=[...])`` / ``Program.analyze``)."""
    from .distributed import check_schedule_consistency

    if ctx.worker_schedules is not None:
        yield from check_schedule_consistency(ctx.worker_schedules)
        return
    if not ctx.workers or len(ctx.workers) <= 1:
        return
    from .distributed import prove_deadlock_free

    _, diags = prove_deadlock_free(ctx.workers)
    yield from diags


@register_check("degenerate-sharding")
def check_degenerate_sharding(ctx):
    """A var marked sharded into more parts than its sharded dim holds
    (or into parts that don't divide it) silently degenerates: some
    workers hold empty/ragged shards while the program still pays every
    collective.  Runs on multi-worker programs only — the cheap
    trainer-count probe comes first so single-worker lint/verify_pass
    sweeps never build the interpreter."""
    nranks = (len(ctx.workers) if ctx.workers
              else int(getattr(ctx.program, "_num_trainers", 1) or 1))
    if nranks <= 1:
        return
    interp = ctx.interp
    for name, v in sorted(interp.sharded_vars().items()):
        s = v.sharding
        if v.shape is None or s.dim is None or s.dim >= len(v.shape):
            continue
        # a dynamic (-1) recorded dim is runtime-sized — the interp
        # resolved it to the assumed batch, which must not be judged
        recorded = ctx.var(name)
        if recorded is not None and recorded.shape is not None \
                and s.dim < len(recorded.shape):
            rd = recorded.shape[s.dim]
            if rd is None or int(rd) < 0:
                continue
        dim_size = int(v.shape[s.dim])
        if dim_size < s.parts:
            yield ctx.diag(
                "degenerate-sharding", Severity.WARNING,
                "%r is sharded %d-way over axis %r but its dim %d has "
                "only %d element(s) — some workers hold empty shards"
                % (name, s.parts, s.axis, s.dim, dim_size),
                var_names=(name,),
                hint="shard a larger dim, or lower the parallelism "
                     "degree for this tensor")
        elif dim_size % s.parts:
            yield ctx.diag(
                "degenerate-sharding", Severity.WARNING,
                "%r dim %d (%d elements) is not divisible by the %d-way "
                "sharding over axis %r — ragged shards"
                % (name, s.dim, dim_size, s.parts, s.axis),
                var_names=(name,),
                hint="pad the dim or choose a degree that divides it")


@register_check("oversized-replicated-persistable")
def check_oversized_replicated_persistable(ctx):
    """On a multi-worker program, a replicated persistable bigger than
    the replication budget (``PADDLE_TPU_REPLICATED_BUDGET`` bytes,
    default: HBM budget / 4 when configured, else 1 GiB) multiplies its
    HBM cost by the worker count for no throughput — shard it (ZeRO /
    tensor parallel / host table)."""
    import os

    from .cost import dtype_bytes, hbm_budget, parse_size

    nranks = (len(ctx.workers) if ctx.workers
              else int(getattr(ctx.program, "_num_trainers", 1) or 1))
    if nranks <= 1:
        return
    interp = ctx.interp
    val = os.environ.get("PADDLE_TPU_REPLICATED_BUDGET", "").strip()
    if val:
        threshold = parse_size(val)
    else:
        budget = hbm_budget(ctx.program)
        threshold = budget // 4 if budget else 1 << 30
    for name, v in sorted(interp.replicated_persistables().items()):
        n = v.numel
        if n is None:
            continue
        size = n * dtype_bytes(v.dtype)
        if size > threshold:
            yield ctx.diag(
                "oversized-replicated-persistable", Severity.WARNING,
                "persistable %r (%d bytes) is replicated on all %d "
                "workers (budget %d bytes per replicated var)"
                % (name, size, nranks, threshold),
                var_names=(name,),
                hint="shard it: BuildStrategy.shard_optimizer_state "
                     "(ZeRO-1), shard_spec/tensor parallel, or a host "
                     "table for embeddings")


@register_check("fused-op-missing-grad")
def check_fused_op_missing_grad(ctx):
    """A fused forward op registered with ``no_grad=True`` silently
    blocks gradient flow: backward.py treats it as non-differentiable,
    so every parameter feeding it gets a zero (or missing) gradient with
    no error.  ERROR when such an op sits on a parameter-derived path of
    a TRAINING program AND a gradient is actually demanded through its
    output (a metrics-only branch is fine; the fusion pipeline's own
    fused ops are all differentiable via the registry's generic vjp —
    this guards custom fused kernels wired in by hand)."""
    from ..ops import registry
    from .fusion import FUSED_FORWARD_OP_TYPES

    order = [rec for rec in ctx.graph.order if rec[0] == 0]
    training = any(
        op.type.endswith("_grad") or op.attrs.get("op_role") == "optimize"
        for _, _, op in order)
    if not training:
        return
    bearing = set()
    for p in ctx.program.all_parameters():
        if getattr(p, "trainable", True) and not p.stop_gradient:
            bearing.add(p.name)
    # gradient demand: vars from which an op WITH a grad twin is
    # reachable.  Only a blocked gradient on a demanded path silently
    # zeroes a param update — a metrics/fetch-only branch reading
    # param-derived values through a no_grad fused op is fine.
    twin_ids = {op.attrs.get("__fwd_op_id__") for _, _, op in order
                if op.type.endswith("_grad")}
    twin_ids.discard(None)
    demanded = set()
    for _, _, op in order:
        if op.attrs.get("__op_id__") in twin_ids:
            demanded.update(n for n in op.input_arg_names
                            if n and n != EMPTY_VAR_NAME)
    for _, _, op in reversed(order):
        if op.type.endswith("_grad"):
            continue
        if demanded.intersection(op.output_arg_names):
            demanded.update(n for n in op.input_arg_names
                            if n and n != EMPTY_VAR_NAME)
    for block_idx, op_idx, op in order:
        if op.type.endswith("_grad") \
                or op.attrs.get("op_role") in ("backward", "optimize"):
            continue
        try:
            opdef = registry.get_op_def(op.type)
        except registry.OpNotRegistered:
            continue
        touches = [n for n in op.input_arg_names if n in bearing]
        if not touches:
            continue
        if opdef.no_grad and (op.type.startswith("fused_")
                              or op.type.startswith("c_fused_")
                              or op.type in FUSED_FORWARD_OP_TYPES) \
                and demanded.intersection(op.output_arg_names):
            yield ctx.diag(
                "fused-op-missing-grad", Severity.ERROR,
                "fused op %r has no registered grad_fn (no_grad=True) "
                "but a parameter gradient path flows through it via %s "
                "— training would silently zero those grads"
                % (op.type, touches[:3]),
                block_idx=block_idx, op_idx=op_idx, op=op,
                var_names=tuple(touches[:3]),
                hint="register the op without no_grad (the registry "
                     "derives <type>_grad via jax.vjp) or give it a "
                     "custom grad_maker")
        if not opdef.no_grad:
            bearing.update(
                n for n in op.output_arg_names if n != EMPTY_VAR_NAME)


@register_check("fusible-pattern-not-fused")
def check_fusible_pattern_not_fused(ctx):
    """Advisory twin of the fusion pipeline: patterns the matchers
    recognize but the pipeline will NOT rewrite — either gated out by
    the cost model (with the model's reason) or because fusion is
    globally disabled.  Points at the anchor op of each pattern."""
    from .fusion import (FusionConfig, fusion_enabled,
                         scan_fusible_patterns)

    report = scan_fusible_patterns(
        ctx.program, FusionConfig(enabled=True), targets=ctx.targets)
    for s in report.skipped:
        yield ctx.diag(
            "fusible-pattern-not-fused", Severity.INFO,
            "fusible %s pattern matched but will not fuse: %s"
            % (s.family, s.reason),
            block_idx=s.block_idx, op_idx=s.op_idx,
            hint="see CompiledProgram.fusion_report() for the full "
                 "pipeline outcome")
    if not fusion_enabled():
        for r in report.applied:
            yield ctx.diag(
                "fusible-pattern-not-fused", Severity.INFO,
                "fusible %s pattern (block %d ops %s -> %s) is disabled "
                "by PADDLE_TPU_FUSION=0"
                % (r.family, r.block_idx, list(r.op_idxs),
                   r.fused_op_type),
                block_idx=r.block_idx,
                op_idx=r.op_idxs[0] if r.op_idxs else None,
                hint="unset PADDLE_TPU_FUSION to enable the rewrite")


@register_check("quantizable-bucket-not-quantized")
def check_quantizable_bucket_not_quantized(ctx):
    """Advisory twin of the quant planner axis (``paddle_tpu/quant``):
    ring-0 gradient buckets big enough that the cost model prices the
    int8 block-quantized exchange as a win, but that will run bf16 —
    because ``PADDLE_TPU_QUANT=0`` disables the subsystem, or because
    no plan mark / env threshold engages it.  Mirrors
    ``fusible-pattern-not-fused``, including the "uncalibrated" reason
    when the autotune ``quant`` family has no measured entry for the
    bucket's shape."""
    from ..quant.blockwise import quant_block, quant_enabled
    from ..quant.collective import quant_min_bytes
    from .cost import dtype_bytes
    from .fusion import _calibration, allreduce_bucket_mb

    if quant_min_bytes(ctx.program) is not None:
        return  # quant is engaged — the rewrite handles these buckets
    block = ctx.program.global_block()
    # group + size-cap the in-place grad allreduces exactly as the
    # fusion bucketer does, so the advisory names the same buckets the
    # rewrite would quantize
    groups = {}
    for i, op in enumerate(block.ops):
        if op.type not in ("c_allreduce_sum", "c_fused_allreduce_sum"):
            continue
        names = op.inputs.get("X", [])
        if not names or set(names) != set(op.outputs.get("Out", [])):
            continue  # only the in-place grad-allreduce shape
        dt = None
        nbytes = 0
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or not v.shape or any(
                    int(d) < 0 for d in v.shape):
                nbytes = 0
                break
            numel = 1
            for d in v.shape:
                numel *= int(d)
            nbytes += numel * dtype_bytes(v.dtype)
            dt = str(v.dtype)
        if not nbytes or dt not in ("float32", "bfloat16"):
            continue
        key = (op.attrs.get("ring_id"), dt)
        groups.setdefault(key, []).append((i, names[0], nbytes))
    if not groups:
        return
    cap = int(allreduce_bucket_mb(ctx.program) * (1 << 20))
    # break-even on the program's cluster spec (or the generic default
    # chip): the same rule a quant-winning plan stamps as min_bytes
    from ..parallel.planner import ClusterSpec, quant_bucket_mark

    spec = getattr(ctx.program, "_cluster_spec", None)
    try:
        cluster = ClusterSpec.coerce(spec) if spec else ClusterSpec(2)
    except Exception:  # noqa: BLE001 - bad spec has its own advisory
        cluster = ClusterSpec(2)
    mark = quant_bucket_mark(cluster, max(cluster.chips, 2))
    blk = quant_block()
    for key, members in sorted(groups.items(),
                               key=lambda kv: kv[1][0][0]):
        buckets = []
        cur, cur_bytes = [], 0
        for item in members:
            if cur and cur_bytes + item[2] > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(item)
            cur_bytes += item[2]
        if cur:
            buckets.append(cur)
        for bucket in buckets:
            total = sum(b for _, _, b in bucket)
            if total < mark["min_bytes"]:
                continue  # cost model says bf16 is right — no noise
            if not quant_enabled():
                reason = "disabled by PADDLE_TPU_QUANT=0"
                hint = "unset PADDLE_TPU_QUANT to let the planner " \
                       "price int8 exchange for this bucket"
            else:
                reason = ("no _quant_buckets plan mark or "
                          "PADDLE_TPU_QUANT_MIN_BYTES threshold engages "
                          "it")
                hint = ("run parallel.auto_transpile (the quant axis "
                        "prices it) or set PADDLE_TPU_QUANT_MIN_BYTES")
                _, _, calibrated = _calibration(
                    "quant", nblocks=total // max(
                        dtype_bytes(key[1]), 1) // blk or 1, block=blk)
                if not calibrated:
                    reason += (" (uncalibrated: autotune family 'quant'"
                               " has no measured entry for this shape)")
            yield ctx.diag(
                "quantizable-bucket-not-quantized", Severity.INFO,
                "ring %r %s gradient bucket (%d members, %d bytes, "
                "anchored at %r) prices as an int8 quantization win "
                "(break-even %d bytes) but runs bf16: %s"
                % (key[0], key[1], len(bucket), total, bucket[0][1],
                   mark["min_bytes"], reason),
                block_idx=0, op_idx=bucket[0][0],
                var_names=(bucket[0][1],), hint=hint)


@register_check("collective-crosses-slow-tier")
def check_collective_crosses_slow_tier(ctx):
    """Advisory twin of the hierarchical-collective rewrite
    (``static_analysis/hierarchy.py``): ring-0 gradient buckets that
    will cross the cluster's slow (DCN) tier as a flat single-ring
    exchange — because the rewrite is disabled, the plan mark pins the
    flat schedule, the topology is asymmetric, or no topology is
    stamped at all so the tier split cannot engage.  Mirrors
    ``fusible-pattern-not-fused`` reason discipline; the hint carries
    the priced per-tier byte/ms delta of the reduce-scatter /
    cross-slice allreduce / allgather decomposition."""
    import os

    from .cost import collective_ici_bytes, dtype_bytes
    from .fusion import allreduce_bucket_mb
    from .hierarchy import (HIER_OP_TYPES, hierarchy_enabled,
                            hierarchy_min_bytes, hierarchy_topology)

    block = ctx.program.global_block()
    nranks = (len(ctx.workers) if ctx.workers
              else int(getattr(ctx.program, "_num_trainers", 0) or 0))
    groups = {}
    for i, op in enumerate(block.ops):
        if op.type not in HIER_OP_TYPES:
            continue
        if op.attrs.get("hier_groups"):
            continue  # already a tier hop of a decomposed exchange
        if op.attrs.get("ring_id") not in (0, None):
            continue  # subgroup rings live inside the fast tier
        names = op.inputs.get("X", [])
        if not names or set(names) != set(op.outputs.get("Out", [])):
            continue  # only the in-place grad-allreduce shape
        nranks = max(nranks, int(op.attrs.get("comm_nranks") or 0))
        if op.attrs.get("pre_scale"):  # GradAllReduce stamps 1/nranks
            nranks = max(
                nranks, int(round(1.0 / float(op.attrs["pre_scale"]))))
        nbytes = 0
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or not v.shape or any(
                    int(d) < 0 for d in v.shape):
                nbytes = 0
                break
            numel = 1
            for d in v.shape:
                numel *= int(d)
            nbytes += numel * dtype_bytes(v.dtype)
        if not nbytes:
            continue
        groups.setdefault(op.attrs.get("ring_id"),
                          []).append((i, names[0], nbytes))
    if not groups or nranks < 4:
        return  # a 2-tier split needs >= 2 chips on each tier
    c = hierarchy_topology(ctx.program, nranks=nranks)
    if c is not None and nranks <= c:
        return  # ring fits inside one slice — nothing crosses DCN
    min_bytes = hierarchy_min_bytes(ctx.program)
    mark = getattr(ctx.program, "_hierarchy", None)
    delta = None
    if c is None:
        reason = ("no topology in ClusterSpec — the ring's tier is "
                  "unknown, so the hierarchical rewrite cannot engage")
        hint = ("stamp program._cluster_spec (or set "
                "PADDLE_TPU_CLUSTER_SPEC) with slices/dcn_gbps so "
                "analyze --plan can price the per-tier split")
    elif nranks % c:
        reason = ("asymmetric topology: nranks=%d not divisible by "
                  "chips_per_slice=%d, so the hierarchical rewrite "
                  "refuses the ring" % (nranks, c))
        hint = ("repair the topology (slices must tile the ring) or "
                "re-plan on the real chip count")
    elif not hierarchy_enabled(ctx.program):
        if mark is False:
            reason = ("the _hierarchy plan mark pins the flat "
                      "schedule (the planner priced flat as the win)")
            hint = ("re-run parallel.auto_transpile if the topology "
                    "or model changed since the plan was stamped")
        else:
            reason = "disabled by PADDLE_TPU_HIERARCHY=0"
            hint = ("unset PADDLE_TPU_HIERARCHY to let "
                    "resolve_fused_program decompose the exchange")
        delta = True
    else:
        return  # rewrite engaged: resolve_fused_program handles these

    # price the per-tier delta on the stamped spec (or its topology
    # defaults) so the hint carries numbers, not vibes
    rates = None
    if delta:
        from ..parallel.planner import ClusterSpec

        raw = getattr(ctx.program, "_cluster_spec", None)
        if raw is None:
            raw = os.environ.get("PADDLE_TPU_CLUSTER_SPEC") or None
        try:
            spec = ClusterSpec.coerce(raw) if raw is not None else None
        except (ValueError, TypeError):
            spec = None
        if spec is None or not spec.has_topology:
            spec = ClusterSpec.coerce(
                {"chips": nranks, "slices": nranks // c})
        rates = spec.tier_wire()

    cap = int(allreduce_bucket_mb(ctx.program) * (1 << 20))
    for ring_id, members in sorted(groups.items(),
                                   key=lambda kv: kv[1][0][0]):
        buckets = []
        cur, cur_bytes = [], 0
        for item in members:
            if cur and cur_bytes + item[2] > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(item)
            cur_bytes += item[2]
        if cur:
            buckets.append(cur)
        for bucket in buckets:
            total = sum(b for _, _, b in bucket)
            if total < min_bytes:
                continue  # threshold says flat is right — no noise
            hint_txt = hint
            if rates is not None:
                s = nranks // c
                flat_dcn = collective_ici_bytes(
                    "c_allreduce_sum", total, nranks)
                hier_dcn = collective_ici_bytes(
                    "c_allreduce_sum", -(-total // c), s)
                hier_ici = 2 * collective_ici_bytes(
                    "c_allgather", total, c)
                dcn_gbps = rates["dcn"][0]
                ici_gbps = rates.get("ici", rates["dcn"])[0]
                hint_txt = (
                    "%s; decomposing cuts slow-tier bytes %d -> %d "
                    "(%.3f -> %.3f ms DCN wire, +%.3f ms ICI)"
                    % (hint, flat_dcn, hier_dcn,
                       flat_dcn / (dcn_gbps * 1e9) * 1e3,
                       hier_dcn / (dcn_gbps * 1e9) * 1e3,
                       hier_ici / (ici_gbps * 1e9) * 1e3))
            yield ctx.diag(
                "collective-crosses-slow-tier", Severity.INFO,
                "ring %r gradient bucket (%d members, %d bytes, "
                "anchored at %r) crosses the slow tier flat "
                "(nranks=%d%s): %s"
                % (ring_id, len(bucket), total, bucket[0][1], nranks,
                   "" if c is None else ", chips_per_slice=%d" % c,
                   reason),
                block_idx=0, op_idx=bucket[0][0],
                var_names=(bucket[0][1],), hint=hint_txt)


def _overlap_pair_sites(block):
    """Per-bucket start/wait op indices in one block, keyed by the
    ``overlap_bucket`` attr that links a pair's twins."""
    starts, waits = {}, {}
    for i, op in enumerate(block.ops):
        if op.type == "c_allreduce_start":
            starts.setdefault(op.attrs.get("overlap_bucket"),
                              []).append(i)
        elif op.type == "c_allreduce_wait":
            waits.setdefault(op.attrs.get("overlap_bucket"),
                             []).append(i)
    return starts, waits


@register_check("collective-start-without-wait")
def check_collective_start_without_wait(ctx):
    """A ``c_allreduce_start`` with no matching ``c_allreduce_wait``
    after it (same ``overlap_bucket``, same block): the in-flight
    reduction has no consumer barrier, so nothing orders the optimizer
    behind the ring — the step would read whatever the async transfer
    happened to deliver.  Extends the collective ring-pairing battery
    to the ISSUE-16 split-collective form."""
    for block in ctx.program.blocks:
        starts, waits = _overlap_pair_sites(block)
        for b, sidxs in sorted(starts.items(),
                               key=lambda kv: kv[1][0]):
            avail = sorted(waits.get(b, []))
            for s in sorted(sidxs):
                w = next((x for x in avail if x > s), None)
                if w is not None:
                    avail.remove(w)
                    continue
                yield ctx.diag(
                    "collective-start-without-wait", Severity.ERROR,
                    "c_allreduce_start (overlap bucket %r) at block %d "
                    "op %d has no c_allreduce_wait after it — the "
                    "in-flight reduction is never barriered"
                    % (b, block.idx, s),
                    block_idx=block.idx, op_idx=s, op=block.ops[s],
                    hint="the overlap pass emits the pair atomically; "
                         "a hand edit dropped or reordered the wait")


@register_check("wait-without-start")
def check_wait_without_start(ctx):
    """A ``c_allreduce_wait`` with no ``c_allreduce_start`` before it
    (same ``overlap_bucket``, same block): the barrier guards a
    transfer nobody launched, so the 'reduced' values it hands the
    optimizer are the raw local gradients."""
    for block in ctx.program.blocks:
        starts, waits = _overlap_pair_sites(block)
        for b, widxs in sorted(waits.items(),
                               key=lambda kv: kv[1][0]):
            sidxs = sorted(starts.get(b, []))
            w = sorted(widxs)[0]
            if not sidxs or sidxs[0] > w:
                yield ctx.diag(
                    "wait-without-start", Severity.ERROR,
                    "c_allreduce_wait (overlap bucket %r) at block %d "
                    "op %d has no c_allreduce_start before it — the "
                    "barrier guards a reduction nobody launched"
                    % (b, block.idx, w),
                    block_idx=block.idx, op_idx=w, op=block.ops[w],
                    hint="the overlap pass emits the pair atomically; "
                         "a hand edit dropped or reordered the start")


@register_check("double-wait")
def check_double_wait(ctx):
    """More than one ``c_allreduce_wait`` for the same
    ``overlap_bucket`` in one block: the pass emits exactly one
    consumer barrier per bucket — a duplicate re-consumes buffers the
    first wait already settled (and under a real async runtime would
    block on a rendezvous that never fires twice)."""
    for block in ctx.program.blocks:
        _, waits = _overlap_pair_sites(block)
        for b, widxs in sorted(waits.items(),
                               key=lambda kv: kv[1][0]):
            for w in sorted(widxs)[1:]:
                yield ctx.diag(
                    "double-wait", Severity.ERROR,
                    "duplicate c_allreduce_wait for overlap bucket %r "
                    "at block %d op %d (first wait at op %d)"
                    % (b, block.idx, w, sorted(widxs)[0]),
                    block_idx=block.idx, op_idx=w, op=block.ops[w],
                    hint="one wait per bucket: drop the duplicate")


@register_check("overlap-opportunity-unexploited")
def check_overlap_opportunity_unexploited(ctx):
    """Advisory twin of the overlap scheduler (ISSUE 16): bucketed
    collectives still in fused synchronous form even though the
    liveness plan finds a window of dead compute to hide the wire
    under — because ``PADDLE_TPU_OVERLAP=0`` disables the pass or a
    proof reverted the bucket — plus the degenerate no-window buckets
    (wait would immediately follow start).  Mirrors
    ``fusible-pattern-not-fused``: INFO, with the pass's own reason."""
    from .overlap import OVERLAPPABLE_OP_TYPES, _plan, overlap_enabled

    block = ctx.program.global_block()
    if not any(op.type in OVERLAPPABLE_OP_TYPES for op in block.ops):
        return
    enabled = overlap_enabled(ctx.program)
    report = getattr(ctx.program, "_overlap_report", None)
    by_vars = {frozenset(d.vars): d for d in report.decisions} \
        if report is not None else {}
    decisions, schedule = _plan(ctx.program, ctx.targets, {})
    planned = {d.bucket for d, _, _, _, _ in schedule}
    for dec in decisions:
        coord = dec.fused_idx
        if dec.status == "no-window":
            yield ctx.diag(
                "overlap-opportunity-unexploited", Severity.INFO,
                "bucket of %d gradient(s) (ring %r, anchored at %r) "
                "stays synchronous: %s"
                % (len(dec.vars), dec.ring_id,
                   dec.vars[0] if dec.vars else "?", dec.note),
                block_idx=coord[0], op_idx=coord[1],
                var_names=dec.vars[:1],
                hint="a smaller allreduce bucket cap closes buckets "
                     "earlier and opens a window")
            continue
        if dec.bucket not in planned or dec.window_ops <= 1:
            continue
        if not enabled:
            reason = "disabled by PADDLE_TPU_OVERLAP=0"
            hint = ("unset PADDLE_TPU_OVERLAP to let the pass hide "
                    "the wire under %d ops of compute"
                    % dec.window_ops)
        else:
            prior = by_vars.get(frozenset(dec.vars))
            if prior is None or not prior.status.startswith(
                    "reverted"):
                continue  # pass will split it at the next resolve
            reason = "%s — %s" % (prior.status, prior.note)
            hint = ("fix the in-window hazard (or the ring asymmetry) "
                    "and re-resolve")
        yield ctx.diag(
            "overlap-opportunity-unexploited", Severity.INFO,
            "bucket of %d gradient(s) (ring %r, anchored at %r) has a "
            "%d-op window of dead compute but runs synchronous: %s"
            % (len(dec.vars), dec.ring_id,
               dec.vars[0] if dec.vars else "?", dec.window_ops,
               reason),
            block_idx=coord[0], op_idx=coord[1],
            var_names=dec.vars[:1], hint=hint)


@register_check("manual-plan-suboptimal")
def check_manual_plan_suboptimal(ctx):
    """Advisory twin of the auto-parallelism planner: a user-transpiled
    (GradAllReduce-style) program priced against the planner's best
    plan for the same cluster.  Fires when the manual plan is more than
    ``PADDLE_TPU_PLAN_ADVISORY_MARGIN`` (default 15%) worse, naming the
    cheaper plan and the predicted delta.

    Opt-in: needs a cluster spec — ``program._cluster_spec`` or
    ``PADDLE_TPU_CLUSTER_SPEC`` (a JSON file path, inline JSON, or a
    bare chip count); with neither, the check is silent (lint must not
    pay for a planner search nobody asked for).  Planner-emitted
    programs (``_auto_plan_key``) and pipeline-stage workers (their
    pre-transpile program is not reconstructible from one stage) are
    skipped.
    """
    import os as _os

    spec = getattr(ctx.program, "_cluster_spec", None)
    if spec is None:
        spec = _os.environ.get("PADDLE_TPU_CLUSTER_SPEC", "").strip()
    if not spec:
        return
    if getattr(ctx.program, "_auto_plan_key", None) is not None:
        return  # the planner priced this very program already
    if getattr(ctx.program, "_pipeline_stage", None) is not None:
        return
    block = ctx.program.global_block()

    # the invertible manual journey: per-grad allreduce inserted over
    # the same vars (X == Out identity under GSPMD) — exactly what
    # DistributeTranspiler(grad_allreduce)/fleet emit.  ONE predicate
    # for both the gate below and the strip that reconstructs the
    # pre-transpile program, so they cannot drift apart
    def _is_identity_allreduce(op):
        return (op.type in ("c_allreduce_sum", "c_fused_allreduce_sum")
                and set(op.input_arg_names) == set(op.output_arg_names))

    manual_allreduces = [op for op in block.ops
                         if _is_identity_allreduce(op)]
    if not manual_allreduces or any(
            op.type in ("send_v2", "recv_v2") for op in block.ops):
        return

    from ..parallel.planner import (ClusterSpec, auto_transpile,
                                    price_worker_set)

    try:
        cluster = ClusterSpec.coerce(spec)
    except Exception as e:  # noqa: BLE001 - bad spec is a finding
        yield ctx.diag(
            "manual-plan-suboptimal", Severity.WARNING,
            "cluster spec %r is unusable: %s" % (spec, e),
            hint="PADDLE_TPU_CLUSTER_SPEC takes a JSON file path, "
                 "inline JSON, or a chip count")
        return

    # strip the identity allreduces to recover the pre-transpile
    # program the planner searches from
    base = ctx.program.clone()
    bb = base.global_block()
    bb.ops = [op for op in bb.ops if not _is_identity_allreduce(op)]
    base._bump_version()

    try:
        from ..parallel.planner import PlanCandidate
        from .fusion import allreduce_bucket_mb

        manual = ctx.program.clone()
        manual._num_trainers = int(
            getattr(ctx.program, "_num_trainers", 0) or 0) \
            or cluster.chips
        # price the manual program as the RUNTIME runs it: the fusion
        # pass buckets its per-grad allreduces too (fuse_all_reduce_ops
        # defaults on), so charging one launch per c_allreduce_sum
        # would fabricate a delta against a behaviorally-equal plan
        manual_as = PlanCandidate(
            "dp", manual._num_trainers,
            bucket_mb=int(allreduce_bucket_mb(ctx.program)))
        _, manual_price = price_worker_set([manual], cluster,
                                           cand=manual_as,
                                           targets=ctx.targets)
        result = auto_transpile(base, cluster, targets=ctx.targets)
    except Exception as e:  # noqa: BLE001 - an opt-in advisory must
        # never abort the whole check battery; degrade to a finding
        yield ctx.diag(
            "manual-plan-suboptimal", Severity.WARNING,
            "planner comparison failed for this program: %s" % e,
            hint="run parallel.auto_transpile directly for the full "
                 "traceback")
        return
    best = result.plan
    try:
        margin = float(_os.environ.get(
            "PADDLE_TPU_PLAN_ADVISORY_MARGIN", "0.15"))
    except ValueError:
        margin = 0.15
    if manual_price.step_ms <= (1.0 + margin) * best.price.step_ms:
        return
    delta = 100.0 * (manual_price.step_ms - best.price.step_ms) \
        / max(best.price.step_ms, 1e-12)
    yield ctx.diag(
        "manual-plan-suboptimal", Severity.INFO,
        "manual parallelism plan prices %.1f%% worse than the "
        "planner's best for this cluster: %s (predicted %.3f ms/step "
        "vs %.3f ms/step manual)"
        % (delta, best.candidate.describe(), best.price.step_ms,
           manual_price.step_ms),
        hint="parallel.auto_transpile(program, cluster_spec) emits the "
             "cheaper plan; see analyze_program --plan for the full "
             "candidate table")


@register_check("decode-shape-unbucketed")
def check_decode_shape_unbucketed(ctx):
    """WARNING: a ``while`` body concatenates a loop-carried tensor with
    fresh per-step data and feeds the result back into the carry — the
    operand's shape grows with the loop index.  That is the classic
    naive KV-append decoder (``k = concat([k, k_step], axis=2)``): on
    TPU every iteration is a NEW shape bucket, so each generated token
    pays a fresh trace+compile plus the host sync that entails — the
    jit cache grows linearly with generated length instead of holding
    one entry.

    The carry set is the while op's ``X``/``Out`` slots plus every
    external var the body writes in place; a concat counts as growing
    when a carried var flows into it (directly or through a chain of
    shape-preserving views) and its result is written back to a carried
    var (directly, via ``assign``, or through such a chain)."""
    _VIEW_OPS = ("assign", "scale", "cast", "reshape", "dropout")
    for block in ctx.program.blocks:
        for op_idx, op in enumerate(block.ops):
            if op.type != "while":
                continue
            carried = set()
            for names in op.inputs.values():
                carried.update(names)
            for names in op.outputs.values():
                carried.update(names)
            carried.discard(EMPTY_VAR_NAME)
            sub = resolve_sub_block(ctx.program, op,
                                    host_block_idx=block.idx)
            if sub is None:
                continue
            # in-place writes to externals are carries too (increment /
            # kv_cache_write idiom): written in the body, defined outside
            local = {v for v in sub.vars}
            for b_op in sub.ops:
                for n in b_op.output_arg_names:
                    if n != EMPTY_VAR_NAME and n not in local:
                        carried.add(n)
            # taint: carried names + anything view-derived from them
            tainted = set(carried)
            grown = {}  # var name -> (op_idx in sub, concat op)
            for b_idx, b_op in enumerate(sub.ops):
                ins = [n for n in b_op.input_arg_names
                       if n != EMPTY_VAR_NAME]
                outs = [n for n in b_op.output_arg_names
                        if n != EMPTY_VAR_NAME]
                if b_op.type == "concat" and tainted.intersection(ins):
                    if set(outs) & carried:  # concat straight into carry
                        yield ctx.diag(
                            "decode-shape-unbucketed", Severity.WARNING,
                            "while body grows a loop-carried tensor: "
                            "concat(axis=%s) over carried %s writes the "
                            "carry itself — each iteration is a new "
                            "shape bucket (per-token recompile + host "
                            "sync on TPU)"
                            % (b_op.attrs.get("axis"),
                               sorted(tainted.intersection(ins))[:2]),
                            block_idx=sub.idx, op_idx=b_idx, op=b_op,
                            var_names=tuple(sorted(set(outs)
                                                   & carried))[:3],
                            hint="keep decode shapes static with a "
                                 "ring-buffer KV cache: "
                                 "layers.create_kv_cache(...) + "
                                 "kv_cache_write(cache, x, cursor) + "
                                 "flash_decode(q, k_cache, v_cache, "
                                 "cursor) — see layers.decode_loop")
                        continue
                    for n in outs:
                        grown[n] = (b_idx, b_op)
                    continue
                hit = grown.keys() & set(ins)
                if hit:
                    # does the grown value reach a carried var?
                    if set(outs) & carried:
                        g_idx, g_op = grown[next(iter(hit))]
                        axis = g_op.attrs.get("axis")
                        yield ctx.diag(
                            "decode-shape-unbucketed", Severity.WARNING,
                            "while body grows a loop-carried tensor: "
                            "concat(axis=%s) over carried %s is written "
                            "back to the carry via %r — each iteration "
                            "is a new shape bucket (per-token "
                            "recompile + host sync on TPU)"
                            % (axis,
                               sorted(tainted.intersection(
                                   g_op.input_arg_names))[:2],
                               b_op.type),
                            block_idx=sub.idx, op_idx=g_idx, op=g_op,
                            var_names=tuple(sorted(set(outs)
                                                   & carried))[:3],
                            hint="keep decode shapes static with a "
                                 "ring-buffer KV cache: "
                                 "layers.create_kv_cache(...) + "
                                 "kv_cache_write(cache, x, cursor) + "
                                 "flash_decode(q, k_cache, v_cache, "
                                 "cursor) — see layers.decode_loop")
                        for n in hit:
                            grown.pop(n, None)
                    elif b_op.type in _VIEW_OPS:
                        for n in outs:
                            grown[n] = grown[next(iter(hit))]
                if b_op.type in _VIEW_OPS and tainted.intersection(ins):
                    tainted.update(outs)


#: size floor (bytes) below which a slot-ring KV cache is not worth
#: paging — the block table + free-list overhead beats the saving
PAGED_MIN_BYTES_ENV = "PADDLE_TPU_PAGED_MIN_BYTES"
DEFAULT_PAGED_MIN_BYTES = 4 << 20


def paged_min_bytes():
    import os

    raw = os.environ.get(PAGED_MIN_BYTES_ENV)
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return DEFAULT_PAGED_MIN_BYTES


@register_check("decode-cache-unpaged")
def check_decode_cache_unpaged(ctx):
    """Advisory twin of the paged-KV serving path (ISSUE 19): a large
    persistable slot-ring KV cache written by ``kv_cache_write`` /
    ``kv_cache_prefill`` that would run through the paged pool
    (``paged_kv_cache_*`` + ``DecodeEngine`` paged mode) instead.  The
    slot ring reserves ``Tmax`` rows per stream no matter how short
    the stream actually runs; the paged pool bounds that internal
    fragmentation at one ``block_len`` block per stream, which is the
    whole streams-per-chip lever.  Mirrors the reason discipline of
    ``fusible-pattern-not-fused``: names the kill switch when
    ``PADDLE_TPU_PAGED_KV=0`` is the blocker, otherwise points at the
    missing paged builders.  Gated by ``PADDLE_TPU_PAGED_MIN_BYTES``
    (default 4 MiB) so toy caches stay quiet."""
    from .cost import dtype_bytes

    floor = paged_min_bytes()
    seen = set()
    for block in ctx.program.blocks:
        for op_idx, op in enumerate(block.ops):
            if op.type not in ("kv_cache_write", "kv_cache_prefill"):
                continue
            names = op.inputs.get("Cache", [])
            if not names or names[0] in seen:
                continue
            name = names[0]
            v = block._find_var_recursive(name)
            if v is None or not getattr(v, "persistable", False):
                continue
            shape = [int(d) for d in (v.shape or [])]
            if len(shape) != 4 or any(d <= 0 for d in shape):
                continue
            seen.add(name)
            slots, heads, tmax, dh = shape
            nbytes = slots * heads * tmax * dh * dtype_bytes(v.dtype)
            if nbytes < floor:
                continue
            try:
                from ..ops.pallas.paged_flash_decode import \
                    paged_block_len
                from ..serving.paging import paged_kv_enabled
                bl = paged_block_len(dh, tmax)
                enabled = paged_kv_enabled()
            except Exception:  # pragma: no cover - serving stack absent
                bl, enabled = 16, True
            # the ring's worst-case idle reservation is the full Tmax
            # row per stream; paging bounds it at one block
            saving = 100.0 * (1.0 - bl / float(tmax)) if tmax else 0.0
            if not enabled:
                reason = ("disabled by the PADDLE_TPU_PAGED_KV=0 kill "
                          "switch")
                hint = ("unset PADDLE_TPU_PAGED_KV to let a "
                        "paged-capable model use the pool")
            else:
                reason = ("the program builds the slot-ring path only "
                          "(no paged_kv_cache_* ops)")
                hint = ("give the model build_prefill_paged/"
                        "build_step_paged (layers.paged_kv_cache_"
                        "prefill/write + layers.paged_flash_decode) — "
                        "DecodeEngine pages it automatically")
            yield ctx.diag(
                "decode-cache-unpaged", Severity.INFO,
                "persistable KV cache %r ([%d, %d, %d, %d], %d bytes) "
                "is slot-ring managed: every stream reserves the full "
                "%d-row depth up front; paging (block_len=%d) would "
                "bound idle reservation at one block — up to %.0f%% "
                "less HBM fragmentation per stream: %s"
                % (name, slots, heads, tmax, dh, nbytes, tmax, bl,
                   saving, reason),
                block_idx=block.idx, op_idx=op_idx, op=op,
                var_names=(name,), hint=hint)
