"""Static per-op and whole-program cost model over the abstract
interpretation (:mod:`.interp`).

In the spirit of XLA's ahead-of-time fusion/memory analysis
(arXiv:2301.13062) and static parallelism-placement cost models
(arXiv:2110.10548): every op gets a :class:`OpCost` — FLOPs, bytes read,
bytes written, and ICI bytes for collectives — and the program gets
totals plus a **liveness-based peak-memory estimate** checked against a
configurable HBM budget.

Conventions (also in README "Static analysis / lint > Analyzer"):

* FLOPs — one multiply-add = 2 FLOPs.  ``mul``/``matmul``/``fc`` are
  ``2·M·K·N`` (+bias adds for fc); ``conv2d`` is
  ``2 · out_numel · Cin·kh·kw``; a generic ``*_grad`` op costs 2x its
  forward; everything else defaults to one FLOP per output element.
* Bytes — dtype-sized reads of every input + writes of every output,
  using LOCAL (per-worker shard) element counts.
* ICI bytes — ring-algorithm transfer volume per worker for an
  ``n``-participant collective of payload ``B`` local bytes:
  allreduce ``2·B·(n-1)/n``; broadcast / allgather / reducescatter /
  all_to_all ``B·(n-1)/n``; p2p ``send_v2``/``recv_v2`` and ``ppermute``
  move exactly ``B``.
* Peak memory — persistables are always resident; a non-persistable
  value is live from its producing op to its last use (fetch targets to
  program end).  ``-1`` dims resolve via ``PADDLE_TPU_ANALYZE_BATCH``.
* HBM budget — ``PADDLE_TPU_HBM_BUDGET`` (bytes; ``K``/``M``/``G``
  suffixes) or ``program._hbm_budget``; the ``peak-memory-over-budget``
  lint check gates on it.
"""

import json
import os

from .interp import interpret_program

__all__ = [
    "OpCost", "CostReport", "estimate_cost", "register_flops",
    "collective_ici_bytes", "dtype_bytes", "parse_size", "hbm_budget",
    "sync_latency_ms", "calibration_factors", "COLLECTIVE_OP_TYPES",
    "P2P_OP_TYPES", "HOST_IO_OP_TYPES", "PlanPrice", "price_plan",
    "price_program", "plan_calibration_factor",
    "PLANNER_CALIBRATION_FAMILY", "OverlapWindow",
    "overlap_window_table", "tier_wire_table",
]

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}


def dtype_bytes(dtype):
    return _DTYPE_BYTES.get(str(dtype), 4)


def parse_size(text):
    """'2G' / '512M' / '16384' -> bytes."""
    s = str(text).strip()
    mult = 1
    if s and s[-1].upper() in "KMGT":
        mult = 1024 ** ("KMGT".index(s[-1].upper()) + 1)
        s = s[:-1]
    return int(float(s) * mult)


def sync_latency_ms():
    """Assumed cost of one device→host sync (``PADDLE_TPU_SYNC_LATENCY_MS``,
    default 1.0 ms) — the knob behind the static dispatch-overhead
    estimate; set it to the deployment's measured round-trip latency."""
    try:
        return float(os.environ.get("PADDLE_TPU_SYNC_LATENCY_MS", "1.0"))
    except ValueError:
        return 1.0


# host-IO op types executed host-side around the jitted step; each one
# is a per-step sync point in the executor's async dispatch loop.
# Derived from the executor's own roster (ops/io_ops.py) so a new host
# op is counted here automatically; NOT `print` — that lowers to
# jax.debug.print inside the jit and never drains the dispatch queue.
from ..ops.io_ops import HOST_IO_OP_TYPES as _EXEC_HOST_IO_OP_TYPES

HOST_IO_OP_TYPES = frozenset(_EXEC_HOST_IO_OP_TYPES)


def calibration_factors():
    """Per-signature predicted-vs-measured calibration factors the
    autotune loop recorded (``{fusion signature: factor}``) — the
    measure-and-learn feedback into this cost model.  The fusion gates
    multiply their predicted deltas by these; ``analyze_program
    --bench-json`` surfaces them so perf PRs can cite how far the
    static model sits from silicon.  Empty when autotune is disabled or
    nothing has been measured."""
    try:
        from ..autotune import calibrations

        return calibrations()
    except Exception:  # pragma: no cover - autotune subsystem broken
        return {}


def hbm_budget(program=None):
    """The configured HBM budget in bytes, or None (check disabled):
    ``program._hbm_budget`` wins over ``PADDLE_TPU_HBM_BUDGET``."""
    if program is not None:
        b = getattr(program, "_hbm_budget", None)
        if b:
            return parse_size(b)
    val = os.environ.get("PADDLE_TPU_HBM_BUDGET", "").strip()
    return parse_size(val) if val else None


# collective op types (the ICI-bytes and schedule-extraction roster);
# symmetric collectives must appear in the same order on every
# participant, p2p ops pair per directed (src, dst) channel
COLLECTIVE_OP_TYPES = frozenset((
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "allreduce", "c_reduce_sum", "c_broadcast",
    "broadcast", "c_allgather", "c_reducescatter", "c_scatter",
    "all_to_all", "ppermute", "c_fused_allreduce_sum",
    "c_allreduce_quant", "c_allreduce_start",
    "c_hier_reducescatter", "c_hier_allgather",
))
# NOT c_allreduce_wait: the wait half of an overlap pair is a consumer
# barrier with zero wire traffic — the start op already carried the
# full ring volume, and counting the wait would double the ICI bytes
# and fabricate a second rendezvous in the schedule prover
P2P_OP_TYPES = frozenset(("send_v2", "recv_v2"))


def _op_quant_block(op):
    """The quantization block size a ``c_allreduce_quant`` op carries
    (0 = the env/default resolved at run time)."""
    try:
        return int(op.attrs.get("quant_block", 0) or 0)
    except (TypeError, ValueError):
        return 0


def collective_ici_bytes(op_type, payload_bytes, nranks):
    """Ring-algorithm ICI transfer volume per worker (see module doc)."""
    n = max(int(nranks), 1)
    b = payload_bytes
    if n <= 1:
        return 0
    if op_type.startswith("c_allreduce") or op_type == "allreduce" \
            or op_type == "c_fused_allreduce_sum":
        return int(2 * b * (n - 1) / n)
    if op_type in P2P_OP_TYPES or op_type == "ppermute":
        return int(b)
    if op_type in COLLECTIVE_OP_TYPES:
        return int(b * (n - 1) / n)
    return 0


# ---------------------------------------------------------------------------
# FLOP rules
# ---------------------------------------------------------------------------

_FLOP_RULES = {}


def register_flops(op_type):
    """Register ``fn(op, ins, outs) -> flops`` (ins/outs: [AbstractVal])
    as the FLOP rule for ``op_type``; the ``register_check`` idiom."""

    def deco(fn):
        _FLOP_RULES[op_type] = fn
        return fn

    return deco


def _out_numel(outs):
    return sum(v.local_numel or 0 for v in outs)


def _matmul_flops(op, ins, outs):
    # 2·M·K·N from the two operand shapes (last-two-dims contraction)
    if len(ins) < 2 or ins[0].shape is None or ins[1].shape is None:
        return 2 * _out_numel(outs)
    a, b = ins[0].shape, ins[1].shape
    if not a or not b:
        return 2 * _out_numel(outs)
    k = a[-1]
    m = 1
    for d in a[:-1]:
        m *= max(int(d), 1)
    n = 1
    for d in b[1:]:
        n *= max(int(d), 1)
    return 2 * m * max(int(k), 1) * n


register_flops("mul")(_matmul_flops)
register_flops("matmul")(_matmul_flops)


@register_flops("fc")
def _fc_flops(op, ins, outs):
    return _matmul_flops(op, ins, outs) + _out_numel(outs)


@register_flops("conv2d")
def _conv2d_flops(op, ins, outs):
    if len(ins) < 2 or ins[1].shape is None or len(ins[1].shape) != 4:
        return 2 * _out_numel(outs)
    cout, cin, kh, kw = (max(int(d), 1) for d in ins[1].shape)
    return 2 * _out_numel(outs) * cin * kh * kw


@register_flops("softmax")
def _softmax_flops(op, ins, outs):
    return 5 * _out_numel(outs)  # max, sub, exp, sum, div


@register_flops("fused_multihead_attention")
def _fused_mha_flops(op, ins, outs):
    # Q [B,H,Tq,dh], K [B,H,Tk,dh]: two matmuls (4·B·H·Tq·Tk·dh) plus
    # the online-softmax arithmetic (~5 FLOPs per score cell)
    if len(ins) < 2 or not ins[0].shape or not ins[1].shape \
            or len(ins[0].shape) != 4 or len(ins[1].shape) != 4:
        return 2 * _out_numel(outs)
    b, h, tq, dh = (max(int(d), 1) for d in ins[0].shape)
    tk = max(int(ins[1].shape[2]), 1)
    return 4 * b * h * tq * tk * dh + 5 * b * h * tq * tk


@register_flops("fused_dropout_add_ln")
def _fused_ln_flops(op, ins, outs):
    # mask+add+two-pass stats+normalize+affine ≈ 8 FLOPs per element
    return 8 * _out_numel(outs)


@register_flops("fused_bias_act")
def _fused_bias_act_flops(op, ins, outs):
    return 2 * _out_numel(outs)


@register_flops("softmax_with_cross_entropy")
def _softmax_xent_flops(op, ins, outs):
    n = ins[0].local_numel if ins and ins[0].local_numel else \
        _out_numel(outs)
    return 5 * (n or 0)


@register_flops("fused_conv_bn_act")
def _fused_conv_bn_act_flops(op, ins, outs):
    # the conv's 2·out·Cin·kh·kw plus ~8 FLOPs/element of BN stats +
    # normalize/affine/act epilogue (outs[0] is Out; MeanOut/VarOut are
    # [C] noise)
    conv = _conv2d_flops(op, ins, outs[:1])
    epilogue = (outs[0].local_numel or 0) if outs else 0
    return conv + 8 * epilogue


@register_flops("fused_embedding_gather")
def _fused_embedding_gather_flops(op, ins, outs):
    return _out_numel(outs)  # a gather moves bytes, not FLOPs


@register_flops("fused_adam")
def _fused_adam_flops(op, ins, outs):
    return 4 * _out_numel(outs)  # ~12 FLOPs per param over 3 out streams


@register_flops("fused_sgd")
def _fused_sgd_flops(op, ins, outs):
    return 2 * _out_numel(outs)


for _t in ("mean", "reduce_mean", "reduce_sum", "reduce_max",
           "reduce_min", "reduce_prod", "sum"):
    register_flops(_t)(
        lambda op, ins, outs: sum(v.local_numel or 0 for v in ins))


@register_flops("c_allreduce_quant")
def _allreduce_quant_flops(op, ins, outs):
    # quantize (absmax/scale/round) + dequant-sum + requant + final
    # dequant ≈ 8 FLOPs per element on top of the wire transfer — the
    # compute tax that lets compute-bound buckets price quant as losing
    return 8 * sum(v.local_numel or 0 for v in ins)


@register_flops("flash_decode_attention")
def _flash_decode_flops(op, ins, outs):
    # Q [B,H,D] (one row) vs the full ring cache [B,H,Tmax,D]: two
    # matvecs (4·B·H·Tmax·dh) plus ~5 FLOPs/score of online softmax.
    # Static analysis charges the Tmax worst case — the mask-to-cursor
    # saving is a runtime property the cost model deliberately ignores
    if len(ins) < 2 or not ins[1].shape or len(ins[1].shape) != 4:
        return 2 * _out_numel(outs)
    b, h, t, dh = (max(int(d), 1) for d in ins[1].shape)
    return 4 * b * h * t * dh + 5 * b * h * t


@register_flops("kv_cache_write")
def _kv_cache_write_flops(op, ins, outs):
    # a dynamic-slice store: moves X's bytes, negligible arithmetic.
    # Charging the cache's numel (the default) would make every decode
    # step look like a full-cache rewrite
    return ins[1].local_numel or 0 if len(ins) > 1 else 0


@register_flops("kv_cache_prefill")
@register_flops("paged_kv_cache_write")
@register_flops("paged_kv_cache_prefill")
def _kv_cache_prefill_flops(op, ins, outs):
    # paged or ring, a cache fill is a scatter of X's rows — the block
    # table adds an [S] (or [L]) index gather, which rounds to zero
    return ins[1].local_numel or 0 if len(ins) > 1 else 0


@register_flops("paged_flash_decode_attention")
def _paged_flash_decode_flops(op, ins, outs):
    # same two matvecs + online softmax as the ring kernel, but the
    # static worst case is the TABLE depth MB·BL (the request's owned
    # blocks), not a monolithic Tmax — paging's capacity win shows up
    # in the cost model as a per-stream, not per-slot, charge.
    # ins: Q [S,H,D], KCache [N,H,BL,D], VCache, Cursor, BlockTable
    # [S,MB]
    if (len(ins) < 5 or not ins[1].shape or len(ins[1].shape) != 4
            or not ins[4].shape or len(ins[4].shape) < 1):
        return 2 * _out_numel(outs)
    _n, h, bl, dh = (max(int(d), 1) for d in ins[1].shape)
    mb = max(int(ins[4].shape[-1]), 1)
    s = max(int(ins[0].shape[0]), 1) if ins[0].shape else 1
    t = mb * bl
    return 4 * s * h * t * dh + 5 * s * h * t


@register_flops("top_k_sampling")
def _top_k_sampling_flops(op, ins, outs):
    # top-k scan + gumbel over k survivors ≈ 2 passes over the logits
    n = ins[0].local_numel if ins and ins[0].local_numel else 0
    return 2 * n


@register_flops("top_p_sampling")
def _top_p_sampling_flops(op, ins, outs):
    # full sort + softmax + cumsum + gumbel ≈ 5 passes over the logits
    n = ins[0].local_numel if ins and ins[0].local_numel else 0
    return 5 * n


def _op_flops(op, ins, outs):
    rule = _FLOP_RULES.get(op.type)
    if rule is not None:
        return int(rule(op, ins, outs))
    if op.type.endswith("_grad"):
        base = _FLOP_RULES.get(op.type[:-len("_grad")])
        if base is not None:
            return 2 * int(base(op, ins, outs))
    if op.type in ("feed", "fetch", "fill_constant", "assign",
                   "c_gen_nccl_id", "c_comm_init", "send_v2", "recv_v2"):
        return 0
    return _out_numel(outs)


# ---------------------------------------------------------------------------
# per-op cost + whole-program report
# ---------------------------------------------------------------------------

class OpCost:
    """Static cost of one op (all byte counts are per-worker/local)."""

    __slots__ = ("record", "flops", "bytes_read", "bytes_written",
                 "ici_bytes", "ring_id", "tier", "group")

    def __init__(self, record, flops, bytes_read, bytes_written,
                 ici_bytes, ring_id=None, tier=None, group=None):
        self.record = record
        self.flops = int(flops)
        self.bytes_read = int(bytes_read)
        self.bytes_written = int(bytes_written)
        self.ici_bytes = int(ici_bytes)
        self.ring_id = ring_id
        # wire tier of a topology-decomposed collective ("ici"/"dcn"/
        # "pod", from the op's `tier` attr) and its subgroup size (from
        # `comm_nranks`); None on flat collectives — the pricer then
        # derives the tier from the ClusterSpec topology, so flat
        # reports stay byte-identical to the pre-topology model
        self.tier = tier
        self.group = group

    def to_dict(self):
        r = self.record
        d = {
            "block_idx": r.block_idx, "op_idx": r.op_idx,
            "op_type": r.op.type, "flops": self.flops,
            "bytes_read": self.bytes_read,
            "bytes_written": self.bytes_written,
            "ici_bytes": self.ici_bytes, "ring_id": self.ring_id,
        }
        if self.tier is not None:
            d["tier"] = self.tier
            d["group"] = self.group
        return d


class OverlapWindow:
    """One start→wait in-flight window of an overlap-scheduled bucket:
    the op coords of the pair, the roofline inputs (FLOPs + HBM bytes)
    of every op scheduled BETWEEN them, and the ring wire volume of the
    collective itself.  :func:`price_plan` hides
    ``min(window compute, wire)`` per window (arXiv 2110.10548's
    compute-vs-wire window model)."""

    __slots__ = ("bucket", "start", "wait", "window_flops",
                 "window_bytes", "wire_bytes", "quant", "var_names",
                 "tier")

    def __init__(self, bucket, start, wait, window_flops, window_bytes,
                 wire_bytes, quant=False, var_names=(), tier=None):
        self.bucket = int(bucket)
        self.start = tuple(start)   # (block_idx, op_idx) of the start
        self.wait = tuple(wait)     # (block_idx, op_idx) of the wait
        self.window_flops = int(window_flops)
        self.window_bytes = int(window_bytes)
        self.wire_bytes = int(wire_bytes)
        self.quant = bool(quant)
        self.var_names = tuple(var_names)
        self.tier = tier  # wire tier the window's ring rides, or None

    def to_dict(self):
        d = {
            "bucket": self.bucket,
            "start": list(self.start), "wait": list(self.wait),
            "window_flops": self.window_flops,
            "window_bytes": self.window_bytes,
            "wire_bytes": self.wire_bytes,
            "quant": self.quant,
            "var_names": list(self.var_names),
        }
        if self.tier is not None:
            d["tier"] = self.tier
        return d


class CostReport:
    """Whole-program totals + the per-op breakdown behind them."""

    def __init__(self, program, op_costs, peak_memory_bytes,
                 persistent_bytes, nranks, batch_size, budget=None,
                 host_sync_points=0, overlap_windows=()):
        self.program = program
        self.op_costs = op_costs
        self.peak_memory_bytes = int(peak_memory_bytes)
        self.persistent_bytes = int(persistent_bytes)
        self.nranks = nranks
        self.batch_size = batch_size
        self.hbm_budget = budget
        # start→wait windows the overlap scheduler opened (empty when
        # the program carries no c_allreduce_start/wait pairs)
        self.overlap_windows = list(overlap_windows)
        # per-step host sync points: host-IO ops the Executor runs
        # around the jitted step (save/load/print) + one for the fetch
        # materialization itself — each drains the async dispatch queue
        self.host_sync_points = int(host_sync_points)

    @property
    def dispatch_overhead_ms(self):
        """Estimated per-step host-sync overhead: ``host_sync_points ×
        PADDLE_TPU_SYNC_LATENCY_MS`` (default 1.0 ms; set it to the
        measured round-trip of the deployment — e.g. ~70 ms over the
        axon tunnel — to project the cost of a sync-per-step loop)."""
        return self.host_sync_points * sync_latency_ms()

    @property
    def total_flops(self):
        return sum(c.flops for c in self.op_costs)

    @property
    def total_bytes_read(self):
        return sum(c.bytes_read for c in self.op_costs)

    @property
    def total_bytes_written(self):
        return sum(c.bytes_written for c in self.op_costs)

    @property
    def total_ici_bytes(self):
        return sum(c.ici_bytes for c in self.op_costs)

    def ici_bytes_per_ring(self):
        per = {}
        for c in self.op_costs:
            if c.ici_bytes:
                per[c.ring_id] = per.get(c.ring_id, 0) + c.ici_bytes
        return per

    def ici_bytes_per_tier(self, cluster=None):
        """Wire bytes per topology tier.  An op's explicit ``tier``
        attr (stamped by the hierarchical decomposition) wins; flat
        collectives derive their tier from ``cluster``'s topology (the
        ring size vs chips-per-slice), or ``"ici"`` with no topology —
        so a flat report on a flat cluster is all-ICI, exactly the
        pre-topology accounting."""
        per = {}
        for c in self.op_costs:
            if not c.ici_bytes:
                continue
            tier = _op_tier(c, cluster, self.nranks)
            per[tier] = per.get(tier, 0) + c.ici_bytes
        return per

    @property
    def over_budget(self):
        return (self.hbm_budget is not None
                and self.peak_memory_bytes > self.hbm_budget)

    def to_dict(self):
        return {
            "total_flops": self.total_flops,
            "total_bytes_read": self.total_bytes_read,
            "total_bytes_written": self.total_bytes_written,
            "total_ici_bytes": self.total_ici_bytes,
            "ici_bytes_per_ring": {
                str(k): v for k, v in self.ici_bytes_per_ring().items()},
            "peak_memory_bytes": self.peak_memory_bytes,
            "persistent_bytes": self.persistent_bytes,
            "host_sync_points": self.host_sync_points,
            "dispatch_overhead_ms": self.dispatch_overhead_ms,
            "hbm_budget": self.hbm_budget,
            "nranks": self.nranks,
            "batch_size": self.batch_size,
            "overlap_windows": [w.to_dict()
                                for w in self.overlap_windows],
            "per_op": [c.to_dict() for c in self.op_costs],
        }

    def bench_json(self):
        """BENCH-style metric lines (one JSON object per line) so perf
        PRs can cite the static baseline next to measured numbers."""
        unit_suffix = " (static, batch=%d, nranks=%d)" % (
            self.batch_size, self.nranks)
        rows = [
            ("static_program_flops", self.total_flops, "FLOPs"),
            ("static_program_bytes_read", self.total_bytes_read, "bytes"),
            ("static_program_bytes_written", self.total_bytes_written,
             "bytes"),
            ("static_program_ici_bytes", self.total_ici_bytes, "bytes"),
            ("static_program_peak_memory", self.peak_memory_bytes,
             "bytes"),
            ("static_host_sync_points", self.host_sync_points,
             "syncs/step"),
            ("static_dispatch_overhead_ms",
             round(self.dispatch_overhead_ms, 3),
             "ms/step est. (host_sync_points x "
             "PADDLE_TPU_SYNC_LATENCY_MS)"),
        ]
        lines = [
            json.dumps({"metric": m, "value": v, "unit": u + unit_suffix})
            for m, v, u in rows
        ]
        if self.overlap_windows:
            # overlap-aware wire accounting (priced at the module's
            # default cluster numbers; calibration divided out so the
            # lines are byte-stable across autotune state)
            price = price_plan(self, calibration=1.0)
            lines.append(json.dumps({
                "metric": "static_exposed_wire_ms",
                "value": round(price.exposed_wire_ms, 6),
                "unit": "ms/step est." + unit_suffix}))
            lines.append(json.dumps({
                "metric": "static_overlap_fraction",
                "value": round(price.overlap_fraction, 6),
                "unit": "fraction of wire hidden under %d windows"
                        % len(self.overlap_windows) + unit_suffix}))
        factors = calibration_factors()
        if factors:
            # the autotune feedback loop: measured/predicted gain per
            # fusion signature, so readers see how far the static model
            # sits from silicon (and which gates run calibrated)
            lines.append(json.dumps({
                "metric": "autotune_calibration_factors",
                "value": len(factors),
                "unit": "calibrated fusion signatures" + unit_suffix,
                "factors": {k: round(v, 4)
                            for k, v in sorted(factors.items())},
            }))
        return "\n".join(lines)

    def format_table(self, top=12):
        """Human cost/memory table: totals then the top-N ops by FLOPs."""
        lines = [
            "cost model (batch=%d, nranks=%d):"
            % (self.batch_size, self.nranks),
            "  FLOPs           %16d" % self.total_flops,
            "  bytes read      %16d" % self.total_bytes_read,
            "  bytes written   %16d" % self.total_bytes_written,
            "  ICI bytes       %16d  %s" % (
                self.total_ici_bytes,
                " ".join("ring %s: %d" % (r, b) for r, b in
                         sorted(self.ici_bytes_per_ring().items(),
                                key=lambda kv: repr(kv[0])))),
            "  peak memory     %16d  (persistables %d%s)" % (
                self.peak_memory_bytes, self.persistent_bytes,
                ", budget %d %s" % (
                    self.hbm_budget,
                    "EXCEEDED" if self.over_budget else "ok")
                if self.hbm_budget is not None else ""),
            "  host syncs/step %16d  (est. %.1f ms dispatch overhead)"
            % (self.host_sync_points, self.dispatch_overhead_ms),
        ]
        ranked = sorted(self.op_costs, key=lambda c: -c.flops)[:top]
        if ranked and ranked[0].flops:
            lines.append("  top ops by FLOPs:")
            for c in ranked:
                if not c.flops:
                    break
                r = c.record
                lines.append(
                    "    block %d op %3d %-22s %12d FLOPs %10d B"
                    % (r.block_idx, r.op_idx, r.op.type, c.flops,
                       c.bytes_read + c.bytes_written))
        return "\n".join(lines)


def _val_bytes(v):
    n = v.local_numel
    if n is None:
        return 0
    return n * dtype_bytes(v.dtype)


def estimate_cost(program, interp=None, targets=(), nranks=None,
                  batch_size=None, budget=None):
    """Run the cost model; returns a :class:`CostReport`.

    ``interp``: reuse an existing :func:`interpret_program` result.
    ``targets``: fetch targets kept live to program end for the peak-
    memory estimate.  ``budget``: HBM budget override in bytes (default
    :func:`hbm_budget`).
    """
    if interp is None:
        interp = interpret_program(program, nranks=nranks,
                                   batch_size=batch_size)
    if budget is None:
        budget = hbm_budget(program)
    nranks = interp.nranks

    op_costs = []
    for rec in interp.records:
        op = rec.op
        bytes_read = sum(_val_bytes(v) for v in rec.ins)
        bytes_written = sum(_val_bytes(v) for v in rec.outs)
        ici = 0
        ring = None
        tier = None
        group = None
        if op.type in COLLECTIVE_OP_TYPES or op.type in P2P_OP_TYPES:
            ring = op.attrs.get("ring_id")
            # a topology-decomposed collective runs on a SUBGROUP of
            # the axis (the slice ring or the cross-slice ring): its
            # `comm_nranks` attr carries the subgroup size the ring
            # formula must use, and `tier` names the wire it rides
            tier = op.attrs.get("tier")
            try:
                group = int(op.attrs.get("comm_nranks") or 0) or None
            except (TypeError, ValueError):
                group = None
            participants = group or nranks
            if op.type in ("c_fused_allreduce_sum",
                           "c_hier_reducescatter") \
                    or (op.type == "c_allreduce_start"
                        and not op.attrs.get("quant")):
                # bucketed allreduce: the coalesced buffer carries the
                # SUM of the member payloads in one launch (the async
                # start half carries the same volume at its hoisted
                # position; the wait half is a zero-byte barrier).
                # Same rule for the hierarchical reduce-scatter: the
                # slice ring moves the whole coalesced bucket once
                payload = sum(_val_bytes(v) for v in rec.ins)
            elif op.type == "c_allreduce_quant" \
                    or op.type == "c_allreduce_start":
                # quantized bucket: the wire carries int8 elements plus
                # the f32-per-block scale sidecar, not the member dtype
                from ..quant.collective import quantized_wire_bytes

                numel = sum(v.local_numel or 0 for v in rec.ins)
                payload, _ = quantized_wire_bytes(
                    numel, participants,
                    block=_op_quant_block(op) or None)
            elif op.type == "c_hier_allgather":
                # the gather-back reassembles the full bucket from the
                # per-rank chunks: volume is the OUTPUT member total
                payload = sum(_val_bytes(v) for v in rec.outs)
            else:
                payload = max(
                    [_val_bytes(v) for v in (rec.ins or rec.outs)] or [0])
            if op.type == "recv_v2" and rec.outs:
                payload = _val_bytes(rec.outs[0])
            ici = collective_ici_bytes(op.type, payload, participants)
        op_costs.append(OpCost(
            rec, _op_flops(op, rec.ins, rec.outs), bytes_read,
            bytes_written, ici, ring_id=ring, tier=tier, group=group))

    # ---- overlap windows (start→wait pairs by overlap_bucket id) ----
    windows = []
    open_starts = {}
    for i, c in enumerate(op_costs):
        op = c.record.op
        bucket = op.attrs.get("overlap_bucket")
        if bucket is None:
            continue
        if op.type == "c_allreduce_start":
            open_starts[int(bucket)] = i
        elif op.type == "c_allreduce_wait" \
                and int(bucket) in open_starts:
            si = open_starts.pop(int(bucket))
            inner = op_costs[si + 1:i]
            start = op_costs[si]
            windows.append(OverlapWindow(
                bucket=int(bucket),
                start=(start.record.block_idx, start.record.op_idx),
                wait=(c.record.block_idx, c.record.op_idx),
                window_flops=sum(x.flops for x in inner),
                window_bytes=sum(x.bytes_read + x.bytes_written
                                 for x in inner),
                wire_bytes=start.ici_bytes,
                quant=bool(start.record.op.attrs.get("quant")),
                var_names=start.record.op.outputs.get("Out", ()),
                tier=start.tier))
    windows.sort(key=lambda w: (w.start, w.bucket))

    # ---- liveness-based peak memory ----
    # interval per non-persistable var: [def index, last read index];
    # feeds start live at 0; targets stay live to the end
    target_names = {getattr(t, "name", t) for t in (targets or ())}
    first_def = {}
    last_use = {}
    # every persistable is scope-resident whether or not an op touches
    # it this step (params, optimizer state, snapshots)
    persist = {n: v for n, v in interp.env.items() if v.persistable}
    for rec in interp.records:
        for v in rec.ins:
            if v.persistable:
                continue
            first_def.setdefault(v.name, 0)   # fed/root value
            last_use[v.name] = rec.index
        for v in rec.outs:
            if v.persistable:
                continue
            first_def.setdefault(v.name, rec.index)
            last_use.setdefault(v.name, rec.index)
    end = len(interp.records)
    for n in target_names:
        if n in first_def:
            last_use[n] = end
    persistent_bytes = sum(_val_bytes(v) for v in persist.values())
    # sweep: delta array of byte changes at each op index
    deltas = [0] * (end + 2)
    for n, d0 in first_def.items():
        v = interp.env.get(n)
        if v is None:
            continue
        b = _val_bytes(v)
        deltas[d0] += b
        deltas[last_use.get(n, d0) + 1] -= b
    peak_live = 0
    running = 0
    for d in deltas:
        running += d
        peak_live = max(peak_live, running)
    peak = persistent_bytes + peak_live

    # per-step host sync points: host-IO ops in the global block (the
    # Executor runs them host-side around the jit, draining the async
    # dispatch queue each step) + one sync for materializing the fetch
    # targets themselves (batched — the single-sync-point contract)
    host_syncs = sum(
        1 for op in program.global_block().ops
        if op.type in HOST_IO_OP_TYPES)
    if targets:
        host_syncs += 1

    return CostReport(program, op_costs, peak, persistent_bytes,
                      nranks, interp.batch_size, budget=budget,
                      host_sync_points=host_syncs,
                      overlap_windows=windows)


# ---------------------------------------------------------------------------
# plan pricing — the auto-parallelism planner's entry points
# (arXiv:2110.10548: search placement candidates against a static cost
# model of the hierarchical system)
# ---------------------------------------------------------------------------

# autotune-cache family the planner's predicted-vs-measured step times
# are recorded under (bench.py --child planner writes them); the factor
# multiplies every PlanPrice so plan rankings track measured silicon
PLANNER_CALIBRATION_FAMILY = "planner"


def plan_calibration_factor():
    """measured/predicted step-time factor the autotune loop recorded
    for the planner's own time model (1.0 when autotune is disabled or
    nothing has been measured).  Recorded by ``bench.py --child
    planner`` under the ``planner`` cache family; consumed by
    :func:`price_plan` so every candidate's predicted cost is scaled by
    how far the static model sat from the last measurement."""
    try:
        from ..autotune import calibration_factor, sweep_signature

        return float(calibration_factor(
            sweep_signature(PLANNER_CALIBRATION_FAMILY, {})))
    except Exception:  # pragma: no cover - autotune subsystem broken
        return 1.0


class PlanPrice:
    """Predicted per-step wall time of one parallelism plan candidate.

    Roofline decomposition over the cluster numbers the caller supplies
    (defaults are a generic contemporary TPU chip):

    * ``flops_ms``   — FLOPs / chip peak;
    * ``hbm_ms``     — (bytes read + written) / HBM bandwidth;
    * ``compute_ms`` — max(flops_ms, hbm_ms) × ``schedule_factor``
      (the candidate's schedule inefficiency, e.g. the GPipe bubble
      ``(M+S-1)/M``);
    * ``ici_ms``     — ICI bytes / link bandwidth;
    * ``launch_ms``  — per-collective launch overhead ×
      ``collective_launches`` (how bucketed allreduce wins);
    * ``exposed_wire_ms`` — the overlap-aware wire term: per start→wait
      window the ring transfer hides under ``min(window compute,
      wire)`` of the compute scheduled inside the window, and only the
      remainder (plus all non-window collective traffic) stays on the
      critical path.  With no overlap windows this equals ``ici_ms``
      exactly — the additive model is the degenerate case;
    * ``overlap_fraction`` — hidden wire / total wire (0.0 when nothing
      overlaps);
    * ``step_ms``    — (compute + exposed_wire + launch) ×
      ``calibration`` (:func:`plan_calibration_factor`).

    Absolute numbers are estimates; the planner only needs the RANKING
    to be faithful, and the calibration factor keeps even the absolute
    scale honest once ``bench --child planner`` has measured a step.
    """

    __slots__ = ("flops_ms", "hbm_ms", "compute_ms", "ici_ms",
                 "launch_ms", "step_ms", "ici_bytes",
                 "peak_memory_bytes", "collective_launches",
                 "schedule_factor", "calibration", "exposed_wire_ms",
                 "overlap_fraction", "tier_wire")

    def __init__(self, flops_ms, hbm_ms, compute_ms, ici_ms, launch_ms,
                 step_ms, ici_bytes, peak_memory_bytes,
                 collective_launches, schedule_factor, calibration,
                 exposed_wire_ms=None, overlap_fraction=0.0,
                 tier_wire=None):
        self.flops_ms = flops_ms
        self.hbm_ms = hbm_ms
        self.compute_ms = compute_ms
        self.ici_ms = ici_ms
        self.launch_ms = launch_ms
        self.step_ms = step_ms
        self.ici_bytes = int(ici_bytes)
        self.peak_memory_bytes = int(peak_memory_bytes)
        self.collective_launches = int(collective_launches)
        self.schedule_factor = schedule_factor
        self.calibration = calibration
        self.exposed_wire_ms = (ici_ms if exposed_wire_ms is None
                                else exposed_wire_ms)
        self.overlap_fraction = overlap_fraction
        # {tier: {"bytes": int, "ms": float}} when tiered pricing ran;
        # None on a flat cluster — to_dict() omits the key then, so
        # flat plans serialize byte-identically to the pre-topology
        # planner (the back-compat contract)
        self.tier_wire = tier_wire

    def to_dict(self, canonical=False):
        """``canonical=True`` divides the calibration factor back out
        of ``step_ms`` and reports calibration 1.0 — the byte-stable
        form the planner's determinism contract serializes (a cached
        calibration scales every candidate alike, so the CHOICE is
        invariant, and the canonical bytes must be too)."""
        cal = (self.calibration
               if canonical and self.calibration else None)
        d = {
            "step_ms": round(self.step_ms / cal if cal
                             else self.step_ms, 6),
            "flops_ms": round(self.flops_ms, 6),
            "hbm_ms": round(self.hbm_ms, 6),
            "compute_ms": round(self.compute_ms, 6),
            "ici_ms": round(self.ici_ms, 6),
            "launch_ms": round(self.launch_ms, 6),
            "exposed_wire_ms": round(self.exposed_wire_ms, 6),
            "overlap_fraction": round(self.overlap_fraction, 6),
            "ici_bytes": self.ici_bytes,
            "peak_memory_bytes": self.peak_memory_bytes,
            "collective_launches": self.collective_launches,
            "schedule_factor": round(self.schedule_factor, 6),
            "calibration": 1.0 if canonical
            else round(self.calibration, 6),
        }
        if self.tier_wire is not None:
            d["tier_wire"] = {
                t: {"bytes": int(v["bytes"]),
                    "ms": round(v["ms"], 6)}
                for t, v in sorted(self.tier_wire.items())}
        return d

    def __repr__(self):
        return ("PlanPrice(step=%.3fms compute=%.3f ici=%.3f "
                "launch=%.3f peak=%dB)") % (
            self.step_ms, self.compute_ms, self.ici_ms, self.launch_ms,
            self.peak_memory_bytes)


def _op_tier(c, cluster, nranks):
    """Wire tier of one collective :class:`OpCost`: the op's explicit
    ``tier`` attr (stamped by the hierarchical decomposition) wins;
    otherwise the cluster topology decides by ring size — a flat
    collective over more ranks than fit one slice rides the slow tier."""
    if c.tier:
        return c.tier
    tier_for = getattr(cluster, "tier_for", None)
    if tier_for is None:
        return "ici"
    return tier_for(c.group or nranks or 1)


def _tier_rates(cluster, ici_gbps, launch_us):
    """``{tier: (gbps, launch_us)}``: the caller's explicit ici numbers
    stay authoritative for the fast tier; the slow tiers come from the
    cluster topology."""
    rates = {"ici": (ici_gbps, launch_us)}
    wire = getattr(cluster, "tier_wire", None)
    if wire is not None:
        for t, v in wire().items():
            if t != "ici":
                rates[t] = v
    return rates


def price_plan(report, peak_tflops=100.0, hbm_gbps=1200.0,
               ici_gbps=100.0, launch_us=5.0, schedule_factor=1.0,
               collective_launches=None, calibration=None,
               extra_ici_bytes=0, extra_launches=0, cluster=None,
               extra_tier_bytes=None, tier_launches=None):
    """Price one worker's :class:`CostReport` against cluster numbers;
    returns a :class:`PlanPrice`.  ``collective_launches`` overrides
    the launch count (the planner models allreduce bucketing this way
    without rewriting the program); ``extra_ici_bytes`` /
    ``extra_launches`` charge traffic the program IR does not carry as
    ops (the planner's ZeRO-1 candidates pay their per-step
    param-allgather here); ``calibration`` overrides
    :func:`plan_calibration_factor`.

    **Tiered wire pricing** engages when ``cluster`` declares a
    topology (``ClusterSpec.has_topology``), when the report carries
    tier-stamped ops, or when the caller passes per-tier deltas: each
    collective is assigned a tier (:func:`_op_tier`), wire time is
    summed per tier at that tier's bandwidth, slow-tier launches pay
    the tier's launch latency, and overlap windows hide wire at their
    own tier's rate.  ``extra_tier_bytes`` (``{tier: ±bytes}``) and
    ``tier_launches`` (``{tier: count}`` — an explicit slow-tier launch
    count overriding the per-op tally) are how the planner prices a
    hierarchical decomposition without rewriting the program.  With a
    flat/absent cluster and no tier inputs the flat single-tier
    arithmetic runs unchanged — bit-identical prices, the kill-switch
    contract."""
    if collective_launches is None:
        collective_launches = sum(
            1 for c in report.op_costs if c.ici_bytes > 0)
    collective_launches += int(extra_launches)
    if calibration is None:
        calibration = plan_calibration_factor()
    flops_ms = report.total_flops / (max(peak_tflops, 1e-9) * 1e9)
    hbm_ms = (report.total_bytes_read + report.total_bytes_written) \
        / (max(hbm_gbps, 1e-9) * 1e6)
    compute_ms = max(flops_ms, hbm_ms) * schedule_factor

    tiered = (bool(getattr(cluster, "has_topology", False))
              or bool(extra_tier_bytes) or bool(tier_launches)
              or any(c.tier for c in report.op_costs))
    tier_wire = None
    tier_surcharge_ms = 0.0
    if not tiered:
        ici_bytes = report.total_ici_bytes + int(extra_ici_bytes)
        ici_ms = ici_bytes / (max(ici_gbps, 1e-9) * 1e6)

        def _wire_ms(w):
            return w.wire_bytes / (max(ici_gbps, 1e-9) * 1e6)
    else:
        rates = _tier_rates(cluster, ici_gbps, launch_us)

        def _rate(t):
            return rates.get(t, rates["ici"])

        tier_bytes = {}
        tier_ops = {}
        for c in report.op_costs:
            if c.ici_bytes <= 0:
                continue
            t = _op_tier(c, cluster, report.nranks)
            tier_bytes[t] = tier_bytes.get(t, 0) + c.ici_bytes
            tier_ops[t] = tier_ops.get(t, 0) + 1
        if extra_ici_bytes:
            tier_bytes["ici"] = (tier_bytes.get("ici", 0)
                                 + int(extra_ici_bytes))
        for t, b in sorted((extra_tier_bytes or {}).items()):
            tier_bytes[t] = max(tier_bytes.get(t, 0) + int(b), 0)
        ici_bytes = sum(tier_bytes.values())
        ici_ms = sum(b / (max(_rate(t)[0], 1e-9) * 1e6)
                     for t, b in tier_bytes.items())
        tier_wire = {t: {"bytes": int(b),
                         "ms": b / (max(_rate(t)[0], 1e-9) * 1e6)}
                     for t, b in tier_bytes.items()}
        # slow-tier launch surcharge: a DCN/pod collective pays that
        # tier's launch latency, not the fast tier's.  The per-op tally
        # is capped by the (possibly bucketed) launch override — a
        # bucketed ring launches `collective_launches` times total, so
        # no more than that many can be slow
        for t, (gbps, t_launch) in sorted(rates.items()):
            if t == "ici" or t_launch <= launch_us:
                continue
            if tier_launches is not None:
                count = int(tier_launches.get(t, 0))
            else:
                count = min(tier_ops.get(t, 0), collective_launches)
            tier_surcharge_ms += count * (t_launch - launch_us) / 1000.0

        def _wire_ms(w):
            t = w.tier or _op_tier(
                _WindowTierProbe(w), cluster, report.nranks)
            return w.wire_bytes / (max(_rate(t)[0], 1e-9) * 1e6)

    launch_ms = (collective_launches * launch_us / 1000.0
                 + tier_surcharge_ms)
    # overlap-aware wire term: each start→wait window hides up to its
    # own compute under the ring transfer (max(compute, wire) per
    # window == compute + exposed remainder); everything outside a
    # window — including extra_ici_bytes like the ZeRO-1 allgather —
    # stays fully exposed.  No windows → exposed == ici_ms exactly.
    hidden_ms = 0.0
    for w in getattr(report, "overlap_windows", None) or ():
        wire_ms = _wire_ms(w)
        win_compute_ms = max(
            w.window_flops / (max(peak_tflops, 1e-9) * 1e9),
            w.window_bytes / (max(hbm_gbps, 1e-9) * 1e6))
        hidden_ms += min(win_compute_ms, wire_ms)
    exposed_wire_ms = max(ici_ms - hidden_ms, 0.0)
    overlap_fraction = (hidden_ms / ici_ms) if ici_ms > 0 else 0.0
    step_ms = (compute_ms + exposed_wire_ms + launch_ms) * calibration
    return PlanPrice(flops_ms, hbm_ms, compute_ms, ici_ms, launch_ms,
                     step_ms, ici_bytes,
                     report.peak_memory_bytes, collective_launches,
                     schedule_factor, calibration,
                     exposed_wire_ms=exposed_wire_ms,
                     overlap_fraction=overlap_fraction,
                     tier_wire=tier_wire)


class _WindowTierProbe:
    """Adapter giving an :class:`OverlapWindow` the ``tier``/``group``
    shape :func:`_op_tier` reads — a tier-less window's ring spans the
    full worker set, so its tier derives from the cluster topology."""

    __slots__ = ("tier", "group")

    def __init__(self, w):
        self.tier = w.tier
        self.group = None


def price_program(program, cluster=None, nranks=None, targets=(),
                  batch_size=None, shard_overrides=None,
                  schedule_factor=1.0, collective_launches=None,
                  budget=None, calibration=None):
    """One-call plan pricing: interpret ``program`` (optionally with
    :func:`~.interp.interpret_program` ``shard_overrides`` candidate
    seeding), run the cost model, and price against ``cluster`` — any
    object with ``peak_tflops`` / ``hbm_gbps`` / ``ici_gbps`` /
    ``launch_us`` / ``hbm_bytes`` attributes (the planner's
    ``ClusterSpec``), or None for the module defaults.  Returns
    ``(CostReport, PlanPrice)``."""
    interp = interpret_program(program, nranks=nranks,
                               batch_size=batch_size,
                               shard_overrides=shard_overrides)
    if budget is None:
        budget = getattr(cluster, "hbm_bytes", None) \
            if cluster is not None else hbm_budget(program)
    report = estimate_cost(program, interp=interp, targets=targets,
                           budget=budget)
    price = price_plan(
        report,
        peak_tflops=getattr(cluster, "peak_tflops", 100.0),
        hbm_gbps=getattr(cluster, "hbm_gbps", 1200.0),
        ici_gbps=getattr(cluster, "ici_gbps", 100.0),
        launch_us=getattr(cluster, "launch_us", 5.0),
        schedule_factor=schedule_factor,
        collective_launches=collective_launches,
        calibration=calibration,
        cluster=cluster)
    return report, price


def tier_wire_table(report, cluster):
    """Per-ring wire rows of the topology-tiered accounting — the
    ``analyze_program --plan`` table and the bench hierarchy gate read
    these.  Each row: ring id, the tier that ring rides, total wire
    bytes, the wire ms at that tier's bandwidth, and whether the ring's
    payload travels quantized (any int8-wire op on the ring)."""
    rates = _tier_rates(cluster,
                        getattr(cluster, "ici_gbps", 100.0),
                        getattr(cluster, "launch_us", 5.0))
    per_ring = {}
    for c in report.op_costs:
        if c.ici_bytes <= 0:
            continue
        row = per_ring.setdefault(
            c.ring_id, {"bytes": 0, "quant": False, "tier": None})
        row["bytes"] += c.ici_bytes
        op = c.record.op
        if op.type == "c_allreduce_quant" or op.attrs.get("quant"):
            row["quant"] = True
        t = _op_tier(c, cluster, report.nranks)
        # rings are tier-homogeneous by construction; the slowest op
        # wins if a hand-built program mixes them
        order = ("ici", "dcn", "pod")
        if row["tier"] is None or (t in order and row["tier"] in order
                                   and order.index(t)
                                   > order.index(row["tier"])):
            row["tier"] = t
    rows = []
    for ring in sorted(per_ring, key=lambda r: (r is None, repr(r))):
        row = per_ring[ring]
        tier = row["tier"] or "ici"
        gbps = rates.get(tier, rates["ici"])[0]
        rows.append({
            "ring": ring,
            "tier": tier,
            "bytes": int(row["bytes"]),
            "ms": round(row["bytes"] / (max(gbps, 1e-9) * 1e6), 6),
            "quant": bool(row["quant"]),
        })
    return rows


def overlap_window_table(report, peak_tflops=100.0, hbm_gbps=1200.0,
                         ici_gbps=100.0):
    """Per-window pricing rows for the overlap windows a
    :class:`CostReport` carries — the ``analyze_program --overlap``
    table and the bench gate both read these.  Each row: bucket id,
    start/wait op coords, the window's roofline compute ms, the ring
    wire ms, the exposed remainder, and a verdict (``hidden`` /
    ``partial`` / ``exposed``)."""
    rows = []
    for w in report.overlap_windows:
        wire_ms = w.wire_bytes / (max(ici_gbps, 1e-9) * 1e6)
        compute_ms = max(
            w.window_flops / (max(peak_tflops, 1e-9) * 1e9),
            w.window_bytes / (max(hbm_gbps, 1e-9) * 1e6))
        hidden = min(compute_ms, wire_ms)
        exposed = wire_ms - hidden
        if wire_ms <= 0 or exposed <= wire_ms * 1e-6:
            verdict = "hidden"
        elif hidden > 0:
            verdict = "partial"
        else:
            verdict = "exposed"
        rows.append({
            "bucket": w.bucket,
            "start": list(w.start), "wait": list(w.wait),
            "vars": len(w.var_names),
            "quant": w.quant,
            "window_compute_ms": round(compute_ms, 6),
            "wire_ms": round(wire_ms, 6),
            "exposed_ms": round(exposed, 6),
            "verdict": verdict,
        })
    return rows
