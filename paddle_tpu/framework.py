"""Graph-construction core: Program ⊃ Block ⊃ {Variable, Operator}.

Mirrors the reference's ``python/paddle/fluid/framework.py`` (Program at
framework.py:2775, Block at :1436, Operator at :985, Variable at :376) but the
descs are plain Python objects rather than views over C++ protobufs: on TPU the
program is lowered wholesale to a jaxpr at Executor.run time, so there is no
C++ interpreter that needs a protobuf IR at runtime.  Serialization to/from a
proto-shaped dict lives in :mod:`paddle_tpu.proto` for save/load parity.

Shape/dtype inference for appended ops is performed with ``jax.eval_shape``
over the op's registered XLA lowering — one inference engine for every op,
replacing the reference's per-op C++ ``InferShape`` functions
(``paddle/fluid/framework/operator.cc:936``).
"""

import contextlib
import itertools

import numpy as np

from . import core
from . import unique_name

# Monotonic id given to every Operator at construction; grad ops copy the
# forward op's id into `__fwd_op_id__` so RNG-consuming lowerings (dropout)
# re-derive identical keys when the vjp recomputes the forward.
_op_id_counter = itertools.count(1)

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "switch_main_program",
    "switch_startup_program",
    "program_guard",
    "name_scope",
    "cpu_places",
    "cuda_places",
    "tpu_places",
    "device_places",
    "in_dygraph_mode",
]

GRAD_VAR_SUFFIX = "@GRAD"
ZERO_VAR_SUFFIX = "@ZERO"

# Sentinel dims used to feed jax.eval_shape when a var has -1 (batch) dims.
# Large odd primes so that shape arithmetic in a lowering (e.g. splitting a
# dim) is unlikely to collide with a real static dim; any output dim equal to
# a sentinel is mapped back to -1.  Static shapes recorded on Variables are
# metadata for graph construction only — execution always re-traces with the
# concrete feed shapes, so a missed mapping cannot affect numerics.
_SHAPE_SENTINELS = (100003, 100019, 100043, 100057, 100069, 100103, 100109)


def grad_var_name(name):
    return name + GRAD_VAR_SUFFIX


_dygraph_tracer_ = None


def in_dygraph_mode():
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    """Debug name scoping (reference framework.py:103)."""
    _name_scope_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_scope_stack.pop()


class Variable:
    """A tensor-valued symbolic variable in a Block (reference
    framework.py:376).  LoD (ragged-sequence) metadata is represented on TPU as
    an optional companion sequence-length var — see layers/sequence ops —
    rather than nested offset vectors on the tensor itself."""

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype="float32",
        lod_level=0,
        persistable=False,
        stop_gradient=False,
        is_data=False,
        type=core.VarDesc.VarType.LOD_TENSOR,
        need_check_feed=False,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = core.convert_np_dtype_to_dtype_(dtype) if dtype is not None else None
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.type = type
        self.need_check_feed = need_check_feed
        # model builders may attach a message appended to feed-shape
        # mismatch errors (e.g. bert's masked-gather head contract)
        self.feed_hint = None
        # op that produced this var last (set by Block.append_op)
        self.op = None

    # ---- reference API surface ----
    def numpy_dtype(self):
        import jax.numpy as jnp

        if self.dtype == "bfloat16":
            return jnp.bfloat16
        return np.dtype(self.dtype)

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def astype(self, dtype):
        from .layers import tensor as _tensor

        return _tensor.cast(self, dtype)

    def __str__(self):
        return "Variable(name=%s, shape=%s, dtype=%s, persistable=%s)" % (
            self.name,
            self.shape,
            self.dtype,
            self.persistable,
        )

    __repr__ = __str__

    # Arithmetic sugar (reference: math_op_patch.py monkeypatching)
    def _binary_op(self, other, op, reverse=False):
        from .layers import math_op_patch

        return math_op_patch.binary_op(self, other, op, reverse)

    def __add__(self, other):
        return self._binary_op(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary_op(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary_op(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary_op(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary_op(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary_op(other, "elementwise_div", reverse=True)

    def __pow__(self, other):
        return self._binary_op(other, "elementwise_pow")

    def __neg__(self):
        from .layers import ops as _ops

        return _ops.scale(self, scale=-1.0)

    def __lt__(self, other):
        return self._binary_op(other, "less_than")

    def __le__(self, other):
        return self._binary_op(other, "less_equal")

    def __gt__(self, other):
        return self._binary_op(other, "greater_than")

    def __ge__(self, other):
        return self._binary_op(other, "greater_equal")


class Parameter(Variable):
    """A persistable, trainable Variable (reference framework.py:3589)."""

    def __init__(self, block, shape, dtype, **kwargs):
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.is_distributed = kwargs.pop("is_distributed", False)
        self.shard_spec = kwargs.pop("shard_spec", None)
        super().__init__(
            block, shape=shape, dtype=dtype, persistable=True, **kwargs
        )
        self.stop_gradient = False


class Operator:
    """One node in a Block: type + named input/output slots (each a list of
    var names) + attrs (reference framework.py:985, OpDesc at
    framework.proto:43)."""

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        # slot name -> list[str] of var names
        self.inputs = {}
        self.outputs = {}
        self.attrs = dict(attrs) if attrs else {}

        def _canon(slots):
            out = {}
            for slot, vs in (slots or {}).items():
                if vs is None:
                    continue
                if not isinstance(vs, (list, tuple)):
                    vs = [vs]
                out[slot] = [v.name if isinstance(v, Variable) else v for v in vs]
            return out

        self.inputs = _canon(inputs)
        self.outputs = _canon(outputs)
        # per-program op ids: unique within the program (RNG key folding,
        # vjp CSE) yet reproducible across separate builds of the same
        # graph — a fixed random_seed then yields identical random ops
        # (the reference's cross-build determinism contract)
        program = block.program if block is not None else None
        if program is None:
            self.attrs.setdefault("__op_id__", next(_op_id_counter))
        elif "__op_id__" in self.attrs:
            # preserved id (clone/deserialize): keep it and raise the
            # program counter floor so later inserts cannot collide
            program._note_op_id(self.attrs["__op_id__"])
        else:
            self.attrs["__op_id__"] = program._next_op_id()
        if _name_scope_stack:
            self.attrs.setdefault("op_namescope", "/".join(_name_scope_stack))

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    @property
    def output_arg_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def input_names(self):
        return list(self.inputs)

    def output_names(self):
        return list(self.outputs)

    def has_attr(self, name):
        return name in self.attrs

    def attr(self, name):
        return self.attrs.get(name)

    def _set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def __repr__(self):
        return "Operator(%s: %s -> %s)" % (self.type, self.inputs, self.outputs)


class Block:
    """An ordered op list plus a var table, with a parent link for nested
    control-flow blocks (reference framework.py:1436, BlockDesc at
    framework.proto:171)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}  # name -> Variable
        self.ops = []  # list[Operator]

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # ---- var management ----
    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        self.program._bump_version()
        return v

    def create_parameter(self, **kwargs):
        # Parameters always live in block 0 (reference framework.py:1727)
        global_block = self.program.global_block()
        prev = global_block.vars.get(kwargs.get("name"))
        p = Parameter(global_block, **kwargs)
        # a re-declared shared parameter keeps its sharding marks (e.g. a
        # second embedding() on the same table without is_distributed=True)
        if getattr(prev, "_is_distributed", False):
            p._is_distributed = True
        if getattr(p, "shard_spec", None) is None:
            p.shard_spec = getattr(prev, "shard_spec", None)
        global_block.vars[p.name] = p
        self.program._bump_version()
        return p

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise ValueError(
                "Variable %r not found in block %d" % (name, self.idx)
            )
        return v

    def has_var(self, name):
        return name in self.vars

    def _find_var_recursive(self, name):
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        return None

    def var_recursive(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("Variable %r not found (recursive)" % name)
        return v

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # ---- op management ----
    def append_op(self, type=None, inputs=None, outputs=None, attrs=None,
                  stop_gradient=False):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump_version()
        self._infer_shapes(op)
        for slot_vs in op.outputs.values():
            for name in slot_vs:
                v = self._find_var_recursive(name)
                if v is not None:
                    v.op = op
                    if stop_gradient:
                        v.stop_gradient = True
        return op

    def _insert_op(self, index, type=None, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        self._infer_shapes(op)
        return op

    def _prepend_op(self, **kwargs):
        return self._insert_op(0, **kwargs)

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def _infer_shapes(self, op):
        """Static shape/dtype inference via jax.eval_shape over the op's
        lowering (replaces the reference's per-op C++ InferShape)."""
        if op.type.endswith("_grad") or op.type in ("feed", "fetch"):
            # grad vars are created with the forward var's shape by
            # backward.py; re-deriving them through vjp tracing would only
            # slow graph construction down
            return
        from .ops import registry

        try:
            registry.infer_shapes(op, self)
        except registry.OpNotRegistered:
            pass  # ops with no lowering (feed/fetch markers etc.)

    def __repr__(self):
        return "Block(idx=%d, ops=%d, vars=%d)" % (
            self.idx,
            len(self.ops),
            len(self.vars),
        )


# per-var attrs Program.clone() must preserve (execution semantics
# depend on them): feed-shape validation + targeted feed errors, ZeRO-1
# accumulator classification, and sharding marks on non-Parameter vars.
# static_analysis/fusion.py aliases this roster for its clone paths.
CLONE_VAR_MARKS = ("need_check_feed", "feed_hint",
                   "_is_optimizer_state", "_is_distributed",
                   "shard_spec")

# program-level marks clone() preserves: the auto-parallelism planner's
# applied runtime knobs (apply_plan) and the HBM budget — a clone of an
# auto-transpiled program must keep running the plan it was priced
# with.  Deliberately NOT _num_trainers/_trainer_id/_pipeline_stage:
# those describe a specific worker's place in a topology, and emitters
# that clone to BUILD a topology (transpile_pipeline, fusion's resolved
# clones via _PROGRAM_MARKS) manage them explicitly.
CLONE_PROGRAM_MARKS = ("_shard_optimizer_state", "_allreduce_bucket_mb",
                       "_hbm_budget", "_max_in_flight",
                       "_serving_hot_loop", "_quant_buckets",
                       "_hierarchy", "_cluster_spec")


class Program:
    """A list of Blocks; block 0 is the global block (reference
    framework.py:2775, ProgramDesc at framework.proto:184)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._seed = 0
        self._version = 0
        # op-role bookkeeping for optimizer/backward phases (reference keeps
        # these as op attrs driven by Program.optimized_guard etc.)
        self._current_role = "forward"
        self.random_seed = 0
        self._is_start_up_program = False
        self._last_op_id = 0

    def _next_op_id(self):
        self._last_op_id += 1
        return self._last_op_id

    def _note_op_id(self, op_id):
        self._last_op_id = max(self._last_op_id, int(op_id))

    # ---- version for jit-cache invalidation ----
    def _bump_version(self):
        self._version += 1

    # ---- block management ----
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def block(self, idx):
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def _create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent_idx=parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._bump_version()
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # ---- iteration / inspection ----
    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self):
        return self.global_block().all_parameters()

    # ---- cloning / pruning ----
    def to_string(self, throw_on_error=True, with_details=False):
        """reference Program.to_string: serialized program text (here the
        JSON ProgramDesc form from proto.py, round-trippable via
        parse_from_string)."""
        import json as _json

        from .proto import program_to_dict

        return _json.dumps(program_to_dict(self), indent=2)

    @staticmethod
    def parse_from_string(s):
        """reference Program.parse_from_string (binary desc → Program);
        here the JSON form emitted by to_string/proto.save_program."""
        import json as _json

        from .proto import program_from_dict

        return program_from_dict(_json.loads(s))

    def clone(self, for_test=False):
        """Deep-copy the program.  With for_test=True, flip is_test attrs on
        dropout/batch_norm-style ops (reference framework.py:3004)."""
        p = Program()
        p.random_seed = self.random_seed
        for mark in CLONE_PROGRAM_MARKS:
            if hasattr(self, mark):
                setattr(p, mark, getattr(self, mark))
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            p.blocks.append(nb)
        for b, nb in zip(self.blocks, p.blocks):
            for name, v in b.vars.items():
                if isinstance(v, Parameter):
                    nv = Parameter(
                        nb,
                        shape=v.shape,
                        dtype=v.dtype,
                        name=v.name,
                        trainable=v.trainable,
                        optimize_attr=v.optimize_attr,
                        regularizer=v.regularizer,
                        stop_gradient=v.stop_gradient,
                    )
                    if getattr(v, "_is_distributed", False):
                        nv._is_distributed = True
                    nv.shard_spec = getattr(v, "shard_spec", None)
                else:
                    nv = Variable(
                        nb,
                        name=v.name,
                        shape=v.shape,
                        dtype=v.dtype,
                        lod_level=v.lod_level,
                        persistable=v.persistable,
                        stop_gradient=v.stop_gradient,
                        is_data=v.is_data,
                        type=v.type,
                    )
                # per-var marks execution semantics depend on — feed
                # validation, ZeRO-1 accumulator classification,
                # sharding marks on non-Parameter vars.  A clone that
                # dropped _is_optimizer_state made every planner-emitted
                # dp+zero1 worker silently NOT shard its optimizer state
                # (fusion.py worked around this per-clone; clone itself
                # is the right place)
                for mark in CLONE_VAR_MARKS:
                    if hasattr(v, mark):
                        setattr(nv, mark, getattr(v, mark))
                nb.vars[name] = nv
            for op in b.ops:
                # for_test prunes the backward+optimize+lr-sched tail
                # (reference clone → _inference_optimize: ops carrying
                # the Backward/Optimize/LRSched roles are dropped), so
                # cloning AFTER minimize yields a pure eval program —
                # without this an "eval" run would keep TRAINING
                # (donating params, advancing the decay counter)
                if for_test and b.idx == 0 and op.attrs.get(
                        "op_role") in ("backward", "optimize",
                                       "lr_sched"):
                    continue
                no = Operator(
                    nb,
                    op.type,
                    {k: list(v) for k, v in op.inputs.items()},
                    {k: list(v) for k, v in op.outputs.items()},
                    dict(op.attrs),
                )
                if for_test and "is_test" in no.attrs:
                    no.attrs["is_test"] = True
                if for_test and op.type in (
                        "dropout", "batch_norm", "layer_norm",
                        "fused_multihead_attention",
                        "fused_dropout_add_ln"):
                    no.attrs["is_test"] = True
                nb.ops.append(no)
        p.current_block_idx = 0
        p._bump_version()
        return p

    def _prune(self, feeded_var_names, targets):
        """Prune to the subgraph producing `targets` from `feeded_var_names`
        (reference framework.py:3106 / C++ prune.cc).  Returns a cloned,
        pruned Program. Only block 0 is pruned; sub-blocks of surviving
        control-flow ops are kept intact."""
        p = self.clone()
        b = p.global_block()
        target_names = set(
            t.name if isinstance(t, Variable) else t for t in targets
        )
        feeds = set(feeded_var_names)
        needed = set(target_names)
        keep = []
        for op in reversed(b.ops):
            if needed & set(op.output_arg_names):
                keep.append(op)
                for n in op.input_arg_names:
                    if n not in feeds:
                        needed.add(n)
        b.ops = list(reversed(keep))
        # drop vars not referenced by surviving ops (keep feeds/targets)
        referenced = set(feeds) | target_names
        for op in b.ops:
            referenced.update(op.input_arg_names)
            referenced.update(op.output_arg_names)
        b.vars = {n: v for n, v in b.vars.items() if n in referenced}
        p._bump_version()
        return p

    def lint(self, targets=None, checks=None, exclude=()):
        """Run the static-analysis check battery over this program and
        return the structured diagnostics (see
        :mod:`paddle_tpu.static_analysis`); raises nothing — gating is
        the caller's choice (``static_analysis.assert_valid`` raises)."""
        from .static_analysis import verify_program

        return verify_program(self, targets=targets, checks=checks,
                              exclude=exclude)

    def analyze(self, targets=None, workers=None, nranks=None,
                batch_size=None, hbm_budget=None, concurrency=False,
                max_in_flight=None, coresident=None,
                certify_zero_sync=False):
        """Whole-program distributed static analysis: abstract
        interpretation (shape/dtype/sharding per var), the static
        FLOP/byte/ICI cost model with a liveness-based peak-memory
        estimate, this worker's per-ring collective schedule, and —
        when ``workers`` supplies the N transpiled per-worker programs
        — the cross-worker collective schedule deadlock-freedom proof.
        ``concurrency=True`` adds the happens-before concurrency
        analysis (:mod:`paddle_tpu.static_analysis.concurrency`):
        in-flight race detection at ``max_in_flight`` (default 2), the
        ``scope-overlap`` isolation proof against ``coresident``
        programs, and — with ``certify_zero_sync=True`` — the zero-sync
        certificate for the steady-state loop.
        Returns a :class:`paddle_tpu.static_analysis.AnalysisReport`;
        raises nothing (gate on ``report.errors``)."""
        from .static_analysis import analyze_program

        return analyze_program(self, targets=targets, workers=workers,
                               nranks=nranks, batch_size=batch_size,
                               hbm_budget=hbm_budget,
                               concurrency=concurrency,
                               max_in_flight=max_in_flight,
                               coresident=coresident,
                               certify_zero_sync=certify_zero_sync)

    def __repr__(self):
        return "Program(blocks=%d, version=%d)" % (len(self.blocks), self._version)

    # serialization — see paddle_tpu/proto.py
    def to_proto_dict(self):
        from . import proto

        return proto.program_to_dict(self)

    @staticmethod
    def parse_from_proto_dict(d):
        from . import proto

        return proto.program_from_dict(d)

    def desc_str(self):
        import json

        return json.dumps(self.to_proto_dict())


_main_program_ = Program()
_startup_program_ = Program()
_startup_program_._is_start_up_program = True


def default_main_program():
    return _main_program_


def default_startup_program():
    return _startup_program_


def switch_main_program(program):
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program):
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


def cpu_places(device_count=None):
    import jax

    try:
        n = device_count or len(jax.devices("cpu"))
    except RuntimeError:
        n = device_count or 1
    return [core.CPUPlace(i) for i in range(n)]


def tpu_places(device_ids=None):
    import jax

    if device_ids is None:
        device_ids = range(jax.device_count())
    return [core.TPUPlace(i) for i in device_ids]


# reference-compatible alias
cuda_places = tpu_places


def device_places(device_ids=None):
    return tpu_places(device_ids)
