from . import fleet

__all__ = ["fleet"]
