from . import fleet
from . import data_generator

__all__ = ["fleet", "data_generator"]
