"""Collective fleet (reference:
``python/paddle/fluid/incubate/fleet/collective/__init__.py``:135 Collective
fleet, :262 CollectiveOptimizer).

TPU-native: `fleet.init` also initializes the jax coordination service when
the role maker reports >1 workers (multi-host), replacing the reference's
gen_nccl_id bootstrap; `CollectiveOptimizer.minimize` runs the wrapped
optimizer then records the DP topology for CompiledProgram — GSPMD performs
the gradient all-reduce, so no graph rewrite is needed (the reference's
transpile step collapses into mesh construction)."""

import os

from ..base.fleet_base import Fleet, DistributedOptimizer, Mode
from .... import io as fluid_io

__all__ = ["fleet", "Collective", "CollectiveOptimizer",
           "DistributedStrategy"]


class DistributedStrategy:
    """reference fleet/collective/__init__.py:25 + BuildStrategy knobs"""

    def __init__(self):
        from ....compiler import BuildStrategy, ExecutionStrategy

        self.exec_strategy = ExecutionStrategy()
        self.build_strategy = BuildStrategy()
        self.use_amp = False
        self.amp_loss_scaling = 2 ** 15
        self.use_recompute = False
        self.recompute_checkpoints = []
        self.use_local_sgd = False
        self.mode = "collective"
        self.collective_mode = "grad_allreduce"
        self.nccl_comm_num = 1
        # auto=True replaces the hand-picked collective_mode with the
        # auto-parallelism planner (parallel.auto_transpile): the
        # candidate search runs over the worker count at minimize time,
        # a DP-family winner is applied in place, and the full
        # PlanResult lands on program._auto_plan (non-DP winners —
        # pipeline stage sets — are emitted there for the caller to
        # deploy; one worker's in-place program cannot express them)
        self.auto = False


class Collective(Fleet):
    def __init__(self):
        super().__init__(Mode.COLLECTIVE)
        self._local_ip = 0

    def init(self, role_maker=None):
        super().init(role_maker)
        self._init_jax_distributed()

    # _init_jax_distributed inherited from Fleet (fleet_base.py): boots
    # the coordination service, re-raising genuine bootstrap failures

    def init_worker(self):
        pass

    def barrier_worker(self, timeout=None):
        """All-worker rendezvous with a bounded wait (reference
        fleet_base barrier_worker, minus the ability to hang forever):
        a peer that died leaves this call stuck in the coordination
        service, so the sync runs under a wall-clock deadline (env
        ``PADDLE_TPU_BARRIER_TIMEOUT_S``, default 600) and surfaces as
        :class:`~paddle_tpu.resilience.watchdog.WorkerLostError` instead
        of an unbounded hang."""
        import jax

        if self.worker_num() <= 1 or jax.process_count() <= 1:
            return

        from ....resilience import retry as _retry
        from ....resilience.watchdog import WorkerLostError

        if timeout is None:
            timeout = float(os.environ.get(
                "PADDLE_TPU_BARRIER_TIMEOUT_S", "600"))

        def _sync():
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("paddle_tpu_fleet_barrier")

        _retry.run_with_timeout(
            _sync, timeout, what="fleet worker barrier",
            error_cls=WorkerLostError)

    def init_server(self, model_dir=None):
        raise NotImplementedError(
            "Collective fleet has no servers; all members are workers"
        )

    def run_server(self):
        raise NotImplementedError(
            "Collective fleet has no servers; all members are workers"
        )

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy)
        self._optimizer._fleet = self
        return self._optimizer

    def save_inference_model(self, executor, dirname, feeded_var_names=None,
                             target_vars=None, main_program=None,
                             export_for_deployment=True):
        return fluid_io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor, main_program,
        )

    def save_persistables(self, executor, dirname, main_program=None,
                          filename=None):
        return fluid_io.save_persistables(executor, dirname, main_program,
                                          filename)


fleet = Collective()


class CollectiveOptimizer(DistributedOptimizer):
    """reference :262 — wraps a regular optimizer; after minimize, the
    program carries the DP topology for mesh construction."""

    def __init__(self, optimizer, strategy=None):
        super().__init__(optimizer, strategy or DistributedStrategy())
        self._fleet = None

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set, callbacks
        )

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        # wrapper chain built LOCALLY per call (reassigning
        # self._optimizer would stack another AMP/recompute wrapper on
        # every minimize); recompute sits INNER, AMP outermost, so the
        # bf16 rewrite scans the flat graph BEFORE segments move into
        # recompute_block sub-blocks
        opt = self._optimizer
        if self._strategy and getattr(self._strategy, "use_recompute",
                                      False):
            # reference fleet strategy: wrap in RecomputeOptimizer with
            # the user-listed checkpoint vars (previously this flag was
            # silently ignored)
            cps = getattr(self._strategy, "recompute_checkpoints",
                          None) or []
            if not cps:
                raise ValueError(
                    "DistributedStrategy.use_recompute needs "
                    "recompute_checkpoints (the segment-boundary vars); "
                    "alternatively build regions with "
                    "fluid.layers.recompute()")
            from ....optimizer import RecomputeOptimizer

            opt = RecomputeOptimizer(opt)
            opt._set_checkpoints(cps)
        if self._strategy and getattr(self._strategy, "use_amp", False):
            from ....contrib import mixed_precision

            opt = mixed_precision.decorate(
                opt,
                init_loss_scaling=float(getattr(
                    self._strategy, "amp_loss_scaling", 2 ** 15)))
        ops, params_grads = opt.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        program = loss.block.program
        if self._fleet is not None:
            program._num_trainers = self._fleet.worker_num()
            program._trainer_id = self._fleet.worker_index()
        if self._strategy and getattr(self._strategy, "auto", False):
            # DistributedStrategy.auto=True: search the placement space
            # instead of assuming grad-allreduce DP
            from ....framework import default_startup_program
            from ....parallel.planner import (apply_plan, auto_transpile,
                                              resolve_cluster_spec)

            nworkers = getattr(program, "_num_trainers", 1) or 1
            if nworkers > 1:
                su = startup_program or default_startup_program()
                result = auto_transpile(
                    program, resolve_cluster_spec(chips=nworkers),
                    startup_program=su, targets=[loss.name])
                apply_plan(program, result, startup_program=su,
                           rank=getattr(program, "_trainer_id", 0))
            return ops, params_grads
        if self._strategy and getattr(self._strategy, "use_local_sgd",
                                      False):
            # reference strategy knob → collective.py LocalSGD:
            # snapshot/train-local/allreduce-deltas appended after the
            # optimizer ops (previously stored but silently ignored)
            from ....framework import default_startup_program
            from ....transpiler.collective import LocalSGD

            LocalSGD().transpile(
                program=program,
                startup_program=startup_program
                or default_startup_program(),
                rank=getattr(program, "_trainer_id", 0),
                nranks=getattr(program, "_num_trainers", 1),
            )
        return ops, params_grads

    def main_program(self):
        from ....framework import default_main_program

        return default_main_program()
