from . import role_maker
from .fleet_base import Fleet, DistributedOptimizer

__all__ = ["role_maker", "Fleet", "DistributedOptimizer"]
