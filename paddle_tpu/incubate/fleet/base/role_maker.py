"""Role makers (reference:
``python/paddle/fluid/incubate/fleet/base/role_maker.py``: MPI:146,
PaddleCloud:337, UserDefined:399).

On TPU the cluster identity ultimately feeds ``jax.distributed.initialize``
(coordination service) instead of gen_nccl_id RPC; the role maker remains
the env-var/user-config parsing layer, same as the reference.
"""

import os

__all__ = [
    "Role",
    "RoleMakerBase",
    "UserDefinedRoleMaker",
    "UserDefinedCollectiveRoleMaker",
    "PaddleCloudRoleMaker",
]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role_is_generated = False
        self._role = Role.WORKER
        self._current_id = 0

    def generate_role(self):
        self._role_is_generated = True

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return len(self._worker_endpoints) or 1

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = server_endpoints or []

    def worker_num(self):
        return self._worker_num


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._worker_endpoints = worker_endpoints or ["127.0.0.1:6170"]
        self._role = Role.WORKER


class PaddleCloudRoleMaker(RoleMakerBase):
    """Parses the PADDLE_* env contract (reference role_maker.py:337):
    PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS,
    PADDLE_PORT, TRAINING_ROLE, PADDLE_PSERVERS_IP_PORT_LIST."""

    def __init__(self, is_collective=True):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._role_is_generated:
            return
        self._role_is_generated = True
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.WORKER if role == "TRAINER" else Role.SERVER
        self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        ps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in ps.split(",") if e]

    def worker_num(self):
        self.generate_role()
        return (
            len(self._worker_endpoints)
            or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        )
