"""Fleet base (reference:
``python/paddle/fluid/incubate/fleet/base/fleet_base.py``)."""

import abc
import os

from ....executor import global_scope

__all__ = ["Fleet", "DistributedOptimizer", "Mode",
           "init_jax_distributed"]


def init_jax_distributed(coordinator_address, num_processes, process_id):
    """Multi-host bootstrap via the jax coordination service (replaces
    the reference's gen_nccl_id_op.cc:188 rank-0 RPC broadcast).

    The bootstrap is the rendezvous where transient faults concentrate
    (a peer restarting, a coordinator port not yet listening), so it
    runs under the resilience retry policy: injected/transient
    connection-level failures back off and re-attempt; a genuinely
    failed bootstrap still re-raises — silently degrading to
    un-synchronized single-host training on an n-host job is the one
    outcome worse than crashing.  Only 'already initialized' is benign.
    """
    import jax

    from ....resilience import faults as _rfaults
    from ....resilience import retry as _rretry

    def _boot():
        # injectable site (barrier_fail): a transient bootstrap failure
        # must be absorbed by the backoff, not kill the worker
        _rfaults.get_injector().maybe_fire("barrier")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    try:
        _rretry.retry_call(_boot, site="fleet.init_jax_distributed")
    except (RuntimeError, ValueError) as e:
        if "already" not in str(e).lower():
            raise


class Mode:
    TRANSPILER = 1
    PSLIB = 2
    COLLECTIVE = 3


class Fleet(abc.ABC):
    def __init__(self, mode):
        self._is_initialized = False
        self._mode = mode
        self._optimizer = None
        self._role_maker = None
        self._executor = None

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def server_num(self):
        return self._role_maker.server_num()

    def server_index(self):
        return self._role_maker.server_index()

    def is_server(self):
        return self._role_maker.is_server()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def split_files(self, files):
        """Shard a filelist across workers (reference fleet_base.py
        split_files) — the data side of multi-host DP."""
        trainer_id = self.worker_index()
        trainers = self.worker_num()
        return files[trainer_id::trainers]

    def init(self, role_maker=None):
        from .role_maker import PaddleCloudRoleMaker

        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        self._is_initialized = True

    def _init_jax_distributed(self):
        """Boot the coordination service when the role maker reports a
        multi-host job; no-op single-host."""
        n = self.worker_num()
        if n <= 1:
            return
        coord = os.environ.get("PADDLE_COORDINATOR_ADDRESS")
        if coord is None:
            eps = self.worker_endpoints()
            coord = eps[0] if eps else None
        if coord is None:
            return
        init_jax_distributed(coord, n, self.worker_index())

    @abc.abstractmethod
    def init_worker(self):
        pass

    @abc.abstractmethod
    def init_server(self, model_dir=None):
        pass

    @abc.abstractmethod
    def run_server(self):
        pass

    @abc.abstractmethod
    def stop_worker(self):
        pass

    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        pass

    @abc.abstractmethod
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        pass

    @abc.abstractmethod
    def save_persistables(self, executor, dirname, main_program=None):
        pass


class DistributedOptimizer(abc.ABC):
    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    @abc.abstractmethod
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        pass

    @abc.abstractmethod
    def apply_gradients(self, params_grads):
        pass

    @abc.abstractmethod
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        pass
