from . import base
from . import collective

__all__ = ["base", "collective"]
