from . import base
from . import collective
from . import parameter_server

__all__ = ["base", "collective", "parameter_server"]
