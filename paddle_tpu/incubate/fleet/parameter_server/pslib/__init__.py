"""PSLib (Downpour) fleet façade (reference:
``python/paddle/fluid/incubate/fleet/parameter_server/pslib/__init__.py``:
PSLib :27, DownpourOptimizer :274).

The reference pslib drives the in-house Downpour parameter server (async
push/pull of sparse tables, ps_pb2 configs, server/worker daemons).  The
TPU substrate has one store — the mesh — so PSLib here shares the
DistributedTranspiler lifecycle (mark sparse tables ``_is_distributed``,
row-shard over the data axis) and keeps pslib-specific surface:

- ``distributed_optimizer(opt, strategy={})`` accepts the pslib dict
  strategy (entries recorded, sparse-table routing is automatic).
- ``shrink_dense_table(decay)`` — the one pslib op with dense-math
  meaning — decays persistable params in the live scope, matching the
  reference's in-place ``scale`` on server tables (:228).
- ``shrink_sparse_table`` warns: TPU tables are dense row-sharded arrays;
  frequency-based row eviction has no equivalent (rows simply stay).
"""

import warnings

from ..distribute_transpiler import (DistributedTranspiler,
                                     TranspilerOptimizer)

__all__ = ["fleet", "PSLib", "DownpourOptimizer"]


class PSLib(DistributedTranspiler):
    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = DownpourOptimizer(optimizer, strategy)
        self._optimizer._fleet = self
        return self._optimizer

    def init_server(self, model_dir=None, **kwargs):
        return super().init_server(model_dir)

    def shrink_sparse_table(self):
        warnings.warn(
            "shrink_sparse_table: TPU tables are dense row-sharded "
            "arrays; frequency-based row eviction is a no-op.")

    def shrink_dense_table(self, decay, scope=None, table_id=None):
        """Decay dense model parameters in place (reference pslib :228
        sends a scale command to the server dense table).  Only true
        ``Parameter`` vars are touched — optimizer accumulators
        (moments, beta-pow) and row-sharded sparse tables are exactly
        what the reference's dense-table scale does NOT reach."""
        import numpy as np

        from .....executor import global_scope
        from .....framework import Parameter, default_main_program

        if table_id is not None:
            warnings.warn(
                "shrink_dense_table: table_id selection is a pslib "
                "server concept; on TPU all dense params form one "
                "logical table, so table_id=%r is ignored" % (table_id,))
        scope = scope or global_scope()
        program = self.main_program or default_main_program()
        for var in program.global_block().vars.values():
            if not isinstance(var, Parameter):
                continue
            if getattr(var, "_is_distributed", False):
                continue  # sparse table, not a dense-table member
            if not scope.has(var.name):
                continue
            val = scope.get(var.name)
            if not hasattr(val, "dtype"):
                continue
            if np.issubdtype(np.dtype(val.dtype), np.floating):
                scope.set(var.name, val * decay)


fleet = PSLib()


class DownpourOptimizer(TranspilerOptimizer):
    """Reference :274 — pslib strategies arrive as plain dicts."""

    def __init__(self, optimizer, strategy=None):
        from .....transpiler import DistributeTranspilerConfig

        if strategy is None or isinstance(strategy, dict):
            self._pslib_strategy = strategy or {}
            strategy = DistributeTranspilerConfig()
        super().__init__(optimizer, strategy)
