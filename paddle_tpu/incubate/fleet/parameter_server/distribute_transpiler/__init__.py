"""PS fleet over the sharded-embedding substrate (reference:
``python/paddle/fluid/incubate/fleet/parameter_server/
distribute_transpiler/__init__.py``: DistributedTranspiler fleet :32,
TranspilerOptimizer :246).

Reference lifecycle: ``fleet.init(role)`` → ``distributed_optimizer(
opt, DistributeTranspilerConfig()).minimize(loss)`` → transpile splits
the program into trainer/pserver halves; server processes call
``init_server()/run_server()`` (blocking listen_and_serv), workers call
``init_worker()`` (connect + fetch params), train on
``fleet.main_program``, then ``stop_worker()``.

TPU-native redesign — same script, no servers:
- ``minimize`` runs the wrapped optimizer, then "transpiles" by marking
  every sparse ``lookup_table`` parameter ``_is_distributed`` (the
  row-sharded GSPMD table replaces the pserver-sliced distributed lookup
  table, ``transpiler/distribute_transpiler.py:353-376``) and recording
  the trainer topology on the program for mesh construction.
- ``fleet.main_program``/``startup_program`` are the original programs:
  there is no program split because there is no second process kind.
- ``init_worker`` boots the jax coordination service when multi-host
  (replacing the worker→pserver connect); ``init_server``/``run_server``
  warn-and-return so a launcher that still spawns PSERVER-role processes
  degrades gracefully instead of wedging a TPU host on a dead RPC loop.
"""

import warnings

from ...base.fleet_base import Fleet, DistributedOptimizer, Mode
from ..... import io as fluid_io

__all__ = ["fleet", "DistributedTranspiler", "TranspilerOptimizer"]


# canonical home is the core transpiler (fleet builds on it, not the
# reverse); re-exported here for existing importers
from .....transpiler.distribute_transpiler import mark_sparse_tables \
    as _mark_sparse_tables


class DistributedTranspiler(Fleet):
    """Drop-in for the reference PS fleet entry point."""

    def __init__(self):
        super().__init__(Mode.TRANSPILER)
        self.main_program = None
        self.startup_program = None

    def init_worker(self):
        """Reference :46 waits for pservers then pulls params; here the
        mesh IS the store, so this is the multi-host bootstrap point
        (``Fleet._init_jax_distributed``)."""
        self._init_jax_distributed()

    def init_server(self, model_dir=None):
        """No pserver process exists on TPU; tables live row-sharded on
        the worker mesh.  Loading a warm-start dir is the one still-
        meaningful piece (reference :71 loads persistables first)."""
        warnings.warn(
            "TPU fleet has no parameter servers; is_distributed tables "
            "row-shard over the worker mesh. init_server is a no-op "
            "(pass model_dir to io.load_persistables on a worker instead)."
        )

    def run_server(self):
        warnings.warn(
            "TPU fleet has no parameter servers; run_server returns "
            "immediately. Launch this process as a TRAINER instead."
        )

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = TranspilerOptimizer(optimizer, strategy)
        self._optimizer._fleet = self
        return self._optimizer

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        return fluid_io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        """Sharded tables save per-process shards (io.py handles the
        is_distributed split; reference :178 re-assembles pserver
        blocks)."""
        return fluid_io.save_persistables(executor, dirname, main_program)

    def _transpile(self, config, programs=None):
        """The TPU 'transpile': mark sparse-lookup params as row-sharded
        and stamp the trainer topology.  No program split.  Of the
        DistributeTranspilerConfig fields, sync_mode=False applies the
        AsyncSGD staleness-1 rewrite (+ enable_dc_asgd compensation);
        slicing knobs describe the pserver program that no longer
        exists."""
        from .....framework import (default_main_program,
                                    default_startup_program)

        main = (programs or {}).get("main") or default_main_program()
        startup = (programs or {}).get("startup") or \
            default_startup_program()
        if config is not None and not getattr(config, "sync_mode", True):
            # async PS mode (communicator.h:160) → staleness-1 delayed
            # gradient exchange, same as DistributeTranspiler(sync_mode
            # =False); enable_dc_asgd adds delay compensation
            from .....transpiler.collective import AsyncSGD

            AsyncSGD(dc_asgd=getattr(
                config, "enable_dc_asgd", False)).transpile(
                program=main, startup_program=startup,
                rank=self.worker_index(), nranks=self.worker_num(),
            )
        _mark_sparse_tables(main)
        main._num_trainers = self.worker_num()
        main._trainer_id = self.worker_index()
        self.main_program = main
        self.startup_program = startup


fleet = DistributedTranspiler()


class TranspilerOptimizer(DistributedOptimizer):
    """Reference :246 — validates the config, runs the inner optimizer,
    then transpiles.  Here the optimizer's sharded-accumulator logic
    (table-shaped moments inherit ``_is_distributed``) does the real PS
    work, so minimize is: mark tables → inner minimize → record topology."""

    def __init__(self, optimizer, strategy=None):
        from .....transpiler import DistributeTranspilerConfig

        if strategy is None:
            strategy = DistributeTranspilerConfig()
        if not isinstance(strategy, DistributeTranspilerConfig):
            raise TypeError(
                "strategy must be a DistributeTranspilerConfig, got %r"
                % (type(strategy),))
        super().__init__(optimizer, strategy)
        self._fleet = None

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        fleet_obj = self._fleet or fleet
        main = loss.block.program
        # mark BEFORE the inner minimize so freshly-created optimizer
        # accumulators for table params inherit _is_distributed
        _mark_sparse_tables(main)
        ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        fleet_obj._transpile(self._strategy, programs={
            "main": main, "startup": startup_program})
        return ops, params_grads
