"""Parameter-server fleet façade (reference:
``python/paddle/fluid/incubate/fleet/parameter_server/__init__.py``).

The reference splits the PS fleet into ``distribute_transpiler`` (native
send/recv PS built by DistributeTranspiler) and ``pslib`` (the Downpour
in-house PS).  On TPU there are no parameter servers: ``is_distributed``
embedding tables row-shard over the worker mesh (GSPMD moves ids/rows
over ICI — see ``layers.embedding``), and dense gradients all-reduce via
the partitioner.  Both submodules here are thin lifecycle façades that
accept the reference API unchanged and route onto that substrate.
"""
