"""DataGenerator (reference:
``python/paddle/fluid/incubate/fleet/../data_generator/__init__.py``) —
the user-subclassed converter from raw log lines to MultiSlot text
records consumed by the dataset pipeline (``dataset.py`` MultiSlot
parser / ``native/src/multislot.cc``).

Users override ``generate_sample(line)`` (→ iterator of
``[(slot_name, [feasign...]), ...]``) and optionally
``generate_batch(samples)``; ``run_from_stdin``/``run_from_memory``
drive the conversion (the reference's streaming MapReduce-style
contract), emitting ``<len> id...`` per slot.
"""

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32
        self._line_limit = None

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int) or line_limit < 1:
            raise ValueError("line_limit must be a positive int")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def run_from_memory(self):
        """Drive generate_sample(None) → generate_batch → stdout."""
        batch_samples = []
        for user_sample in self.generate_sample(None)():
            if user_sample is None:
                continue
            batch_samples.append(user_sample)
            if len(batch_samples) == self.batch_size_:
                for sample in self.generate_batch(batch_samples)():
                    sys.stdout.write(self._gen_str(sample))
                batch_samples = []
        if batch_samples:
            for sample in self.generate_batch(batch_samples)():
                sys.stdout.write(self._gen_str(sample))

    def run_from_stdin(self):
        """One raw input line per generate_sample call (streaming)."""
        batch_samples = []
        for n, line in enumerate(sys.stdin, 1):
            if self._line_limit and n > self._line_limit:
                break
            for user_sample in self.generate_sample(line)():
                if user_sample is None:
                    continue
                batch_samples.append(user_sample)
                if len(batch_samples) == self.batch_size_:
                    for sample in self.generate_batch(batch_samples)():
                        sys.stdout.write(self._gen_str(sample))
                    batch_samples = []
        if batch_samples:
            for sample in self.generate_batch(batch_samples)():
                sys.stdout.write(self._gen_str(sample))

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def generate_sample(self, line):
        raise NotImplementedError(
            "generate_sample() must be overridden: return a zero-arg "
            "iterator of [(slot_name, [feasign, ...]), ...]")

    def generate_batch(self, samples):
        """Default: pass samples through one by one."""

        def local_iter():
            for sample in samples:
                yield sample

        return local_iter


def _check_sample(line):
    if not isinstance(line, (list, tuple)):
        raise ValueError(
            "the output of process() must be list or tuple, e.g. "
            "[('words', [1926, 8, 17]), ('label', [1])]")


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, line):
        """[(name, [str_id...]), ...] → '<len> id... <len> id...\\n'."""
        _check_sample(line)
        parts = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(elements)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    def _gen_str(self, line):
        """Typed variant: tracks per-slot uint64/float in _proto_info
        (a float element anywhere flips the slot to float, as in the
        reference's progressive type refinement)."""
        _check_sample(line)
        if self._proto_info is None:
            self._proto_info = [(name, "uint64") for name, _ in line]
        elif len(self._proto_info) != len(line):
            raise ValueError(
                "field count changed between samples: %d vs %d"
                % (len(self._proto_info), len(line)))
        parts = []
        for i, (name, elements) in enumerate(line):
            if not elements:
                raise ValueError(
                    "slot %r is empty — pad it in process()" % name)
            if name != self._proto_info[i][0]:
                raise ValueError(
                    "field name changed between samples: %r vs %r"
                    % (self._proto_info[i][0], name))
            parts.append(str(len(elements)))
            for elem in elements:
                if isinstance(elem, float):
                    self._proto_info[i] = (name, "float")
                elif not isinstance(elem, int):
                    raise ValueError(
                        "element of slot %r must be int or float, got %r"
                        % (name, type(elem)))
                parts.append(str(elem))
        return " ".join(parts) + "\n"
