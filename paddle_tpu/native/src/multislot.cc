// MultiSlot text parser: the CTR ingest hot loop.
//
// Reference: paddle/fluid/framework/data_feed.cc
// MultiSlotDataFeed::ParseOneInstance (:525) — each line holds, per slot,
// "<count> v1 v2 ..." where values are float or uint64 feasigns, parsed
// with strtof/strtoull.  The reference runs one DataFeed per worker thread
// over a shared filelist; here one call parses a whole file with a thread
// pool over line ranges and returns dense, zero-padded [N, slot_len]
// buffers ready to become device arrays (the TPU path wants rectangular
// batches, not LoD).
//
// C ABI for ctypes.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct ParsedFile {
  int num_slots = 0;
  long num_examples = 0;
  std::vector<int> slot_types;  // 0 = float, 1 = uint64
  std::vector<int> slot_lens;   // padded length per slot
  // per-slot dense buffer [num_examples * slot_len]
  std::vector<std::vector<float>> fbuf;
  std::vector<std::vector<int64_t>> ibuf;
};

// parse lines in [begin, end) of `text` into per-slot vectors
void parse_range(const char* text, size_t begin, size_t end, int num_slots,
                 const int* slot_types, const int* slot_lens,
                 std::vector<std::vector<float>>* fout,
                 std::vector<std::vector<int64_t>>* iout, long* count,
                 int* errors) {
  size_t pos = begin;
  while (pos < end) {
    size_t eol = pos;
    while (eol < end && text[eol] != '\n') ++eol;
    if (eol > pos) {  // non-empty line
      const char* p = text + pos;
      const char* line_end = text + eol;
      char* endp = const_cast<char*>(p);
      bool ok = true;
      for (int s = 0; s < num_slots && ok; ++s) {
        char* before = endp;
        long n = strtol(endp, &endp, 10);
        // the reference enforces a nonzero count per slot
        // (data_feed.cc:538); no-progress parse = non-numeric line
        if (endp == before || endp > line_end || n <= 0) {
          ok = false;
          break;
        }
        int L = slot_lens[s];
        if (slot_types[s] == 0) {
          auto& v = (*fout)[s];
          size_t base = v.size();
          v.resize(base + L, 0.0f);
          for (long j = 0; j < n; ++j) {
            before = endp;
            float val = strtof(endp, &endp);
            // bail on malformed/short lines instead of spinning n times
            // or eating tokens of the next line (strto* skip newlines)
            if (endp == before || endp > line_end) { ok = false; break; }
            if (j < L) v[base + j] = val;
          }
        } else {
          auto& v = (*iout)[s];
          size_t base = v.size();
          v.resize(base + L, 0);
          for (long j = 0; j < n; ++j) {
            before = endp;
            int64_t val = static_cast<int64_t>(strtoull(endp, &endp, 10));
            if (endp == before || endp > line_end) { ok = false; break; }
            if (j < L) v[base + j] = val;
          }
        }
      }
      if (ok) {
        ++*count;
      } else {
        ++*errors;
        // roll back partially written slots to keep buffers rectangular
        for (int s = 0; s < num_slots; ++s) {
          size_t want = static_cast<size_t>(*count) * slot_lens[s];
          if (slot_types[s] == 0) (*fout)[s].resize(want);
          else (*iout)[s].resize(want);
        }
      }
    }
    pos = eol + 1;
  }
}

}  // namespace

extern "C" {

// Parse `path` with the given schema.  threads <= 0 → hardware default.
void* ms_parse_file(const char* path, const int* slot_types,
                    const int* slot_lens, int num_slots, int threads) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long fsize = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string text(fsize, '\0');
  if (fsize > 0 && fread(&text[0], 1, fsize, f) != (size_t)fsize) {
    fclose(f);
    return nullptr;
  }
  fclose(f);

  int nthreads = threads > 0
                     ? threads
                     : static_cast<int>(std::thread::hardware_concurrency());
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  // split at line boundaries
  std::vector<size_t> starts{0};
  for (int t = 1; t < nthreads; ++t) {
    size_t pos = fsize * t / nthreads;
    while (pos < (size_t)fsize && text[pos] != '\n') ++pos;
    if (pos < (size_t)fsize) ++pos;
    starts.push_back(pos);
  }
  starts.push_back(fsize);

  int actual = static_cast<int>(starts.size()) - 1;
  std::vector<std::vector<std::vector<float>>> fparts(actual);
  std::vector<std::vector<std::vector<int64_t>>> iparts(actual);
  std::vector<long> counts(actual, 0);
  std::vector<int> errors(actual, 0);
  std::vector<std::thread> pool;
  for (int t = 0; t < actual; ++t) {
    fparts[t].resize(num_slots);
    iparts[t].resize(num_slots);
    pool.emplace_back(parse_range, text.data(), starts[t], starts[t + 1],
                      num_slots, slot_types, slot_lens, &fparts[t],
                      &iparts[t], &counts[t], &errors[t]);
  }
  for (auto& th : pool) th.join();
  // release the raw text before merging: bounds peak memory to roughly
  // two copies of the parsed data (per-thread parts + merged buffers)
  std::string().swap(text);

  ParsedFile* out = new ParsedFile();
  out->num_slots = num_slots;
  out->slot_types.assign(slot_types, slot_types + num_slots);
  out->slot_lens.assign(slot_lens, slot_lens + num_slots);
  out->fbuf.resize(num_slots);
  out->ibuf.resize(num_slots);
  for (int t = 0; t < actual; ++t) out->num_examples += counts[t];
  for (int s = 0; s < num_slots; ++s) {
    if (slot_types[s] == 0) {
      auto& dst = out->fbuf[s];
      dst.reserve(out->num_examples * slot_lens[s]);
      for (int t = 0; t < actual; ++t) {
        dst.insert(dst.end(), fparts[t][s].begin(), fparts[t][s].end());
        std::vector<float>().swap(fparts[t][s]);  // free as we merge
      }
    } else {
      auto& dst = out->ibuf[s];
      dst.reserve(out->num_examples * slot_lens[s]);
      for (int t = 0; t < actual; ++t) {
        dst.insert(dst.end(), iparts[t][s].begin(), iparts[t][s].end());
        std::vector<int64_t>().swap(iparts[t][s]);
      }
    }
  }
  return out;
}

long ms_num_examples(void* handle) {
  return static_cast<ParsedFile*>(handle)->num_examples;
}

// copy slot s ([num_examples, slot_len], float32 or int64) into out
int ms_copy_slot(void* handle, int s, void* out) {
  ParsedFile* p = static_cast<ParsedFile*>(handle);
  if (s < 0 || s >= p->num_slots) return -1;
  size_t n = static_cast<size_t>(p->num_examples) * p->slot_lens[s];
  if (p->slot_types[s] == 0)
    memcpy(out, p->fbuf[s].data(), n * sizeof(float));
  else
    memcpy(out, p->ibuf[s].data(), n * sizeof(int64_t));
  return 0;
}

void ms_free(void* handle) { delete static_cast<ParsedFile*>(handle); }

}  // extern "C"
