// C++-only train demo (reference: paddle/fluid/train/demo/demo_trainer.cc)
//
// Runs a SERIALIZED fit-a-line training program with no Python script:
// main() lives here, the binary embeds the CPython runtime and drives the
// paddle_tpu framework purely through the CPython C API (imports, method
// calls, buffer construction) — the TPU-framework analogue of the
// reference linking libpaddle_fluid and calling framework::Executor::Run.
// The compute itself still executes through jax/XLA, exactly as the
// reference demo's kernels execute through its op library.
//
// Usage: demo_trainer <model_dir> [steps]
//   where <model_dir> holds "main_program" and "startup_program" files
//   written by paddle_tpu.proto.save_program, with data vars "x" [B,13]
//   and "y" [B,1] (the reference demo's fit-a-line contract) and a
//   "mean" op producing the loss.

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <string>

namespace {

void Fatal(const char* what) {
  std::fprintf(stderr, "demo_trainer: %s\n", what);
  if (PyErr_Occurred()) PyErr_Print();
  std::exit(1);
}

PyObject* Import(const char* name) {
  PyObject* m = PyImport_ImportModule(name);
  if (!m) Fatal((std::string("cannot import ") + name).c_str());
  return m;
}

// call obj.method(args...) with a new reference result
PyObject* Call(PyObject* obj, const char* method, PyObject* args) {
  PyObject* fn = PyObject_GetAttrString(obj, method);
  if (!fn) Fatal((std::string("no attribute ") + method).c_str());
  PyObject* res = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (!res) Fatal((std::string("call failed: ") + method).c_str());
  return res;
}

// find the loss var name: first "mean" op's Out (reference demo_trainer.cc
// scans Block(0).AllOps() the same way)
std::string FindLossName(PyObject* program) {
  PyObject* block = Call(program, "global_block", PyTuple_New(0));
  PyObject* ops = PyObject_GetAttrString(block, "ops");
  if (!ops) Fatal("block has no ops");
  Py_ssize_t n = PyList_Size(ops);
  std::string loss;
  for (Py_ssize_t i = 0; i < n && loss.empty(); ++i) {
    PyObject* op = PyList_GetItem(ops, i);  // borrowed
    PyObject* type = PyObject_GetAttrString(op, "type");
    if (type && PyUnicode_Check(type) &&
        std::string(PyUnicode_AsUTF8(type)) == "mean") {
      PyObject* outs = Call(op, "output", Py_BuildValue("(s)", "Out"));
      if (PyList_Size(outs) > 0)
        loss = PyUnicode_AsUTF8(PyList_GetItem(outs, 0));
      Py_DECREF(outs);
    }
    Py_XDECREF(type);
  }
  Py_DECREF(ops);
  Py_DECREF(block);
  if (loss.empty()) Fatal("no mean op found — is this fit-a-line?");
  return loss;
}

}  // namespace

int main(int argc, char** argv) {
  const char* dir = argc > 1 ? argv[1] : ".";
  const int steps = argc > 2 ? std::atoi(argv[2]) : 10;
  if (steps < 2) Fatal("steps must be >= 2 (loss-decrease check)");
  const int batch = 2;  // reference demo feeds x[2,13], y[2,1]

  Py_Initialize();

  if (std::getenv("PADDLE_TPU_DEMO_FORCE_CPU")) {
    // the image pins jax_platforms=axon (TPU tunnel); tests force the
    // CPU backend in-process, before the framework's first device use
    PyObject* jaxm = Import("jax");
    PyObject* cfg = PyObject_GetAttrString(jaxm, "config");
    if (!cfg) Fatal("jax.config missing");
    Py_DECREF(
        Call(cfg, "update", Py_BuildValue("(ss)", "jax_platforms", "cpu")));
    Py_DECREF(cfg);
  }

  PyObject* proto = Import("paddle_tpu.proto");
  PyObject* fluid = Import("paddle_tpu");
  PyObject* np = Import("numpy");

  std::string main_path = std::string(dir) + "/main_program";
  std::string startup_path = std::string(dir) + "/startup_program";
  PyObject* main_prog =
      Call(proto, "load_program", Py_BuildValue("(s)", main_path.c_str()));
  PyObject* startup_prog = Call(
      proto, "load_program", Py_BuildValue("(s)", startup_path.c_str()));

  std::string loss_name = FindLossName(main_prog);

  // exe = fluid.Executor(fluid.CPUPlace()); exe.run(startup)
  PyObject* place = Call(fluid, "CPUPlace", PyTuple_New(0));
  PyObject* exe = Call(fluid, "Executor", Py_BuildValue("(O)", place));
  Py_DECREF(Call(exe, "run", Py_BuildValue("(O)", startup_prog)));

  // synthetic fit-a-line batch, built through the numpy API:
  // x = arange(batch*13).reshape(batch,13).astype(float32) / 26.0
  PyObject* x = Call(np, "arange", Py_BuildValue("(i)", batch * 13));
  x = Call(x, "reshape", Py_BuildValue("(ii)", batch, 13));
  x = Call(x, "astype", Py_BuildValue("(s)", "float32"));
  x = PyNumber_TrueDivide(x, PyFloat_FromDouble(26.0));
  if (!x) Fatal("x construction failed");
  PyObject* y = Call(np, "arange", Py_BuildValue("(i)", batch));
  y = Call(y, "reshape", Py_BuildValue("(ii)", batch, 1));
  y = Call(y, "astype", Py_BuildValue("(s)", "float32"));

  PyObject* feed = PyDict_New();
  PyDict_SetItemString(feed, "x", x);
  PyDict_SetItemString(feed, "y", y);
  PyObject* fetch = PyList_New(1);
  PyList_SetItem(fetch, 0, PyUnicode_FromString(loss_name.c_str()));

  double first = 0.0, last = 0.0;
  for (int i = 0; i < steps; ++i) {
    // exe.run(main_prog, feed=feed, fetch_list=[loss])
    PyObject* kwargs = PyDict_New();
    PyDict_SetItemString(kwargs, "feed", feed);
    PyDict_SetItemString(kwargs, "fetch_list", fetch);
    PyObject* run = PyObject_GetAttrString(exe, "run");
    PyObject* args = Py_BuildValue("(O)", main_prog);
    PyObject* out = PyObject_Call(run, args, kwargs);
    Py_DECREF(run);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    if (!out) Fatal("training step failed");
    PyObject* loss_arr = PyList_GetItem(out, 0);  // borrowed
    PyObject* loss_f = Call(loss_arr, "item", PyTuple_New(0));
    double loss = PyFloat_AsDouble(loss_f);
    Py_DECREF(loss_f);
    Py_DECREF(out);
    std::printf("step: %d loss: %f\n", i, loss);
    if (i == 0) first = loss;
    last = loss;
  }

  if (!(last < first)) Fatal("loss did not decrease");
  std::printf("demo_trainer ok: loss %f -> %f\n", first, last);

  Py_DECREF(feed);
  Py_DECREF(fetch);
  Py_Finalize();
  return 0;
}
