// RecordIO: chunked, CRC-checked record file format.
//
// Reference: paddle/fluid/recordio/{header,chunk,writer,scanner}.{h,cc} —
// same layout concepts: a file is a sequence of chunks; each chunk has a
// header {magic, num_records, compressor, checksum, payload_size} followed
// by the payload of length-prefixed records.  Compression (snappy/gzip in
// the reference) is declared in the header; this implementation writes
// kNoCompress and rejects compressed chunks it cannot decode (the TPU data
// path feeds from local uncompressed shards).
//
// C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagicNumber = 0x01020304;  // header.h:23
constexpr uint32_t kNoCompress = 0;

// CRC32 (IEEE, zlib-compatible), small table implementation.
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32_update(uint32_t crc, const unsigned char* buf, size_t len) {
  crc_init();
  crc = crc ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i)
    crc = crc_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

struct Writer {
  FILE* f = nullptr;
  std::vector<std::string> records;
  size_t pending_bytes = 0;
  size_t max_chunk_records;
  size_t max_chunk_bytes;

  bool flush_chunk() {
    if (records.empty()) return true;
    std::string payload;
    payload.reserve(pending_bytes + records.size() * 4);
    for (const auto& r : records) {
      uint32_t len = static_cast<uint32_t>(r.size());
      payload.append(reinterpret_cast<const char*>(&len), 4);
      payload.append(r);
    }
    uint32_t crc = crc32_update(
        0, reinterpret_cast<const unsigned char*>(payload.data()),
        payload.size());
    uint32_t header[5] = {kMagicNumber,
                          static_cast<uint32_t>(records.size()), kNoCompress,
                          crc, static_cast<uint32_t>(payload.size())};
    if (fwrite(header, sizeof(header), 1, f) != 1) return false;
    if (!payload.empty() &&
        fwrite(payload.data(), payload.size(), 1, f) != 1)
      return false;
    records.clear();
    pending_bytes = 0;
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<std::string> chunk_records;  // records of the current chunk
  size_t cursor = 0;                       // next record within chunk
  bool error = false;

  // loads the next chunk; returns false on eof or error
  bool load_chunk() {
    uint32_t header[5];
    size_t got = fread(header, sizeof(uint32_t), 5, f);
    if (got == 0) return false;  // clean EOF
    if (got != 5 || header[0] != kMagicNumber || header[2] != kNoCompress) {
      error = true;
      return false;
    }
    uint32_t num = header[1], crc = header[3], size = header[4];
    std::string payload(size, '\0');
    if (size > 0 && fread(&payload[0], 1, size, f) != size) {
      error = true;
      return false;
    }
    uint32_t actual = crc32_update(
        0, reinterpret_cast<const unsigned char*>(payload.data()),
        payload.size());
    if (actual != crc) {
      error = true;
      return false;
    }
    chunk_records.clear();
    chunk_records.reserve(num);
    size_t pos = 0;
    for (uint32_t i = 0; i < num; ++i) {
      if (pos + 4 > payload.size()) { error = true; return false; }
      uint32_t len;
      memcpy(&len, payload.data() + pos, 4);
      pos += 4;
      if (pos + len > payload.size()) { error = true; return false; }
      chunk_records.emplace_back(payload.data() + pos, len);
      pos += len;
    }
    cursor = 0;
    return true;
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, int max_chunk_records,
                      int max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  w->max_chunk_records = max_chunk_records > 0 ? max_chunk_records : 1000;
  w->max_chunk_bytes =
      max_chunk_bytes > 0 ? max_chunk_bytes : (32u << 20);
  return w;
}

int rio_write(void* handle, const char* data, long len) {
  Writer* w = static_cast<Writer*>(handle);
  w->records.emplace_back(data, static_cast<size_t>(len));
  w->pending_bytes += static_cast<size_t>(len);
  if (w->records.size() >= w->max_chunk_records ||
      w->pending_bytes >= w->max_chunk_bytes) {
    return w->flush_chunk() ? 0 : -1;
  }
  return 0;
}

int rio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  bool ok = w->flush_chunk();
  fclose(w->f);
  delete w;
  return ok ? 0 : -1;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Scanner* s = new Scanner();
  s->f = f;
  return s;
}

// size of the next record, -1 on EOF, -2 on corruption
long rio_next_size(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  while (s->cursor >= s->chunk_records.size()) {
    if (!s->load_chunk()) return s->error ? -2 : -1;
  }
  return static_cast<long>(s->chunk_records[s->cursor].size());
}

// copies the next record into out (caller sized it via rio_next_size) and
// advances; returns 0 ok
int rio_next_copy(void* handle, char* out) {
  Scanner* s = static_cast<Scanner*>(handle);
  if (s->cursor >= s->chunk_records.size()) return -1;
  const std::string& r = s->chunk_records[s->cursor++];
  memcpy(out, r.data(), r.size());
  return 0;
}

void rio_scanner_close(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
