// Bounded blocking byte-buffer queue — the native reader-queue role of the
// reference (paddle/fluid/framework/blocking_queue.h and the
// LoDTensorBlockingQueue bound at pybind.cc:591): producer threads push
// serialized batches, the trainer pops them with backpressure.  Plain C ABI
// for ctypes (no pybind11 in this image).

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

struct Buffer {
  std::vector<uint8_t> data;
};

struct Queue {
  explicit Queue(size_t capacity) : capacity(capacity), closed(false) {}
  size_t capacity;
  bool closed;
  std::deque<Buffer> items;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
};

}  // namespace

extern "C" {

void* ptq_create(size_t capacity) { return new Queue(capacity); }

void ptq_destroy(void* h) { delete static_cast<Queue*>(h); }

// 1 = pushed, 0 = queue closed.
int ptq_push(void* h, const uint8_t* data, size_t len) {
  Queue* q = static_cast<Queue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_full.wait(lk,
                   [q] { return q->closed || q->items.size() < q->capacity; });
  if (q->closed) return 0;
  Buffer b;
  b.data.assign(data, data + len);
  q->items.push_back(std::move(b));
  q->not_empty.notify_one();
  return 1;
}

// Returns the popped length, 0 when the queue is closed AND drained.
// The payload is copied into out (caller sizes it via ptq_peek_len).
int64_t ptq_pop(void* h, uint8_t* out, size_t max_len) {
  Queue* q = static_cast<Queue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [q] { return q->closed || !q->items.empty(); });
  if (q->items.empty()) return 0;
  Buffer b = std::move(q->items.front());
  q->items.pop_front();
  q->not_full.notify_one();
  size_t n = b.data.size() < max_len ? b.data.size() : max_len;
  std::memcpy(out, b.data.data(), n);
  return static_cast<int64_t>(n);
}

// Length of the front item without popping (blocks like pop); 0 = closed
// and drained.
int64_t ptq_peek_len(void* h) {
  Queue* q = static_cast<Queue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [q] { return q->closed || !q->items.empty(); });
  if (q->items.empty()) return 0;
  return static_cast<int64_t>(q->items.front().data.size());
}

size_t ptq_size(void* h) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

void ptq_close(void* h) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

int ptq_is_closed(void* h) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->closed ? 1 : 0;
}

}  // extern "C"
