"""Native (C++) runtime components, loaded via ctypes.

The reference implements its data-plane hot paths natively — RecordIO
(``paddle/fluid/recordio/``) and the MultiSlot DataFeed parser
(``paddle/fluid/framework/data_feed.cc``).  This package holds their
TPU-framework equivalents as a small C++ library (``src/*.cc``) built
on demand with g++ (no pybind11 in this image — plain C ABI + ctypes).

Every entry point has a pure-Python fallback so the framework works even
where a toolchain is unavailable; ``is_native()`` reports which path is
active.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_paddle_tpu_native.so")
_SOURCES = ["recordio.cc", "multislot.cc", "blocking_queue.cc"]

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _build():
    srcs = [os.path.join(_HERE, "src", s) for s in _SOURCES]
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++14", "-pthread",
           "-o", _SO_PATH] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _newest_mtime(paths):
    return max(os.path.getmtime(p) for p in paths)


def get_lib():
    """Returns the loaded ctypes library, building it if needed; None if
    native support is unavailable."""
    global _lib, _build_attempted
    with _lib_lock:
        if _lib is not None:
            return _lib
        srcs = [os.path.join(_HERE, "src", s) for s in _SOURCES]
        stale = (not os.path.exists(_SO_PATH)
                 or os.path.getmtime(_SO_PATH) < _newest_mtime(srcs))
        if stale:
            if _build_attempted:
                return None
            _build_attempted = True
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        # signatures
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.c_int]
        lib.rio_write.restype = ctypes.c_int
        lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_long]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_next_size.restype = ctypes.c_long
        lib.rio_next_size.argtypes = [ctypes.c_void_p]
        lib.rio_next_copy.restype = ctypes.c_int
        lib.rio_next_copy.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.ms_parse_file.restype = ctypes.c_void_p
        lib.ms_parse_file.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_int]
        lib.ms_num_examples.restype = ctypes.c_long
        lib.ms_num_examples.argtypes = [ctypes.c_void_p]
        lib.ms_copy_slot.restype = ctypes.c_int
        lib.ms_copy_slot.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_void_p]
        lib.ms_free.argtypes = [ctypes.c_void_p]
        lib.ptq_create.restype = ctypes.c_void_p
        lib.ptq_create.argtypes = [ctypes.c_size_t]
        lib.ptq_destroy.argtypes = [ctypes.c_void_p]
        lib.ptq_push.restype = ctypes.c_int
        lib.ptq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_size_t]
        lib.ptq_pop.restype = ctypes.c_int64
        lib.ptq_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_size_t]
        lib.ptq_peek_len.restype = ctypes.c_int64
        lib.ptq_peek_len.argtypes = [ctypes.c_void_p]
        lib.ptq_size.restype = ctypes.c_size_t
        lib.ptq_size.argtypes = [ctypes.c_void_p]
        lib.ptq_close.argtypes = [ctypes.c_void_p]
        lib.ptq_is_closed.restype = ctypes.c_int
        lib.ptq_is_closed.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def is_native():
    return get_lib() is not None


# ---------------------------------------------------------------------------
# RecordIO
# ---------------------------------------------------------------------------

_RIO_MAGIC = 0x01020304  # reference header.h kMagicNumber


class RecordIOWriter:
    """Chunked record writer (reference recordio/writer.h)."""

    def __init__(self, path, max_chunk_records=1000, max_chunk_bytes=None):
        self._path = path
        self._max_records = max_chunk_records
        self._max_bytes = max_chunk_bytes or (32 << 20)
        lib = get_lib()
        self._lib = lib
        if lib is not None:
            self._h = lib.rio_writer_open(
                path.encode(), max_chunk_records, self._max_bytes)
            if not self._h:
                raise IOError("cannot open %s" % path)
        else:
            self._f = open(path, "wb")
            self._records = []
            self._pending = 0

    def write(self, data):
        if isinstance(data, str):
            data = data.encode()
        if self._lib is not None:
            if self._lib.rio_write(self._h, data, len(data)) != 0:
                raise IOError("recordio write failed")
            return
        self._records.append(bytes(data))
        self._pending += len(data)
        if (len(self._records) >= self._max_records
                or self._pending >= self._max_bytes):
            self._flush()

    def _flush(self):
        if not self._records:
            return
        import struct
        import zlib

        payload = b"".join(
            struct.pack("<I", len(r)) + r for r in self._records)
        header = struct.pack(
            "<IIIII", _RIO_MAGIC, len(self._records), 0,
            zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
        self._f.write(header + payload)
        self._records = []
        self._pending = 0

    def close(self):
        if self._lib is not None:
            if self._h:
                h, self._h = self._h, None  # C side frees even on error
                if self._lib.rio_writer_close(h) != 0:
                    raise IOError("recordio flush failed")
        elif self._f is not None:
            self._flush()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class RecordIOScanner:
    """Sequential record reader (reference recordio/scanner.h)."""

    def __init__(self, path):
        lib = get_lib()
        self._lib = lib
        if lib is not None:
            self._h = lib.rio_scanner_open(path.encode())
            if not self._h:
                raise IOError("cannot open %s" % path)
        else:
            self._f = open(path, "rb")
            self._chunk = []
            self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._lib is not None:
            size = self._lib.rio_next_size(self._h)
            if size == -1:
                raise StopIteration
            if size < 0:
                raise IOError("corrupt recordio chunk")
            buf = ctypes.create_string_buffer(int(size))
            if self._lib.rio_next_copy(self._h, buf) != 0:
                raise StopIteration
            return buf.raw[:size]
        # python fallback
        import struct
        import zlib

        while self._cursor >= len(self._chunk):
            head = self._f.read(20)
            if not head:
                raise StopIteration
            if len(head) < 20:  # truncated header
                raise IOError("corrupt recordio chunk")
            magic, num, comp, crc, size = struct.unpack("<IIIII", head)
            if magic != _RIO_MAGIC or comp != 0:
                raise IOError("corrupt recordio chunk")
            payload = self._f.read(size)
            if len(payload) != size or (zlib.crc32(payload)
                                        & 0xFFFFFFFF) != crc:
                raise IOError("corrupt recordio chunk")
            self._chunk = []
            pos = 0
            for _ in range(num):
                (ln,) = struct.unpack_from("<I", payload, pos)
                pos += 4
                self._chunk.append(payload[pos:pos + ln])
                pos += ln
            self._cursor = 0
        rec = self._chunk[self._cursor]
        self._cursor += 1
        return rec

    def close(self):
        if self._lib is not None:
            if self._h:
                self._lib.rio_scanner_close(self._h)
                self._h = None
        else:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# MultiSlot parser
# ---------------------------------------------------------------------------

def _wrap_u64(x):
    u = int(x) & 0xFFFFFFFFFFFFFFFF
    return u - (1 << 64) if u >= (1 << 63) else u


def parse_multislot_file(path, slot_types, slot_lens, threads=0):
    """Parse a MultiSlot text file into dense per-slot arrays.

    slot_types: 'float'/'uint64' (or 0/1) per slot; slot_lens: padded length
    per slot.  Returns list of np arrays [N, slot_len] (float32 / int64).
    """
    types = [0 if str(t).startswith(("f", "0")) else 1 for t in slot_types]
    lens = [int(l) for l in slot_lens]
    lib = get_lib()
    if lib is not None:
        n = len(types)
        ctypes_types = (ctypes.c_int * n)(*types)
        ctypes_lens = (ctypes.c_int * n)(*lens)
        h = lib.ms_parse_file(path.encode(), ctypes_types, ctypes_lens, n,
                              threads)
        if not h:
            raise IOError("cannot parse %s" % path)
        try:
            N = lib.ms_num_examples(h)
            out = []
            for s in range(n):
                if types[s] == 0:
                    arr = np.empty((N, lens[s]), np.float32)
                else:
                    arr = np.empty((N, lens[s]), np.int64)
                lib.ms_copy_slot(h, s, arr.ctypes.data_as(ctypes.c_void_p))
                out.append(arr)
            return out
        finally:
            lib.ms_free(h)
    # python fallback — skip-and-continue on malformed lines, matching the
    # native parser's error path
    rows = [[] for _ in types]
    with open(path) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            pos = 0
            vals = []
            ok = True
            for s in range(len(types)):
                if pos >= len(toks):
                    ok = False
                    break
                try:
                    cnt = int(toks[pos])
                except ValueError:
                    ok = False
                    break
                if cnt <= 0:  # reference enforces nonzero counts
                    ok = False
                    break
                pos += 1
                v = toks[pos:pos + cnt]
                if len(v) != cnt:
                    ok = False
                    break
                pos += cnt
                try:
                    if types[s] == 0:
                        vals.append([float(x) for x in v])
                    else:
                        # uint64 feasigns wrap two's-complement into int64,
                        # matching the native parser's C cast (jax has no
                        # uint64 on TPU; hash ids below 2^63 to avoid
                        # negative embedding rows)
                        vals.append([_wrap_u64(x) for x in v])
                except ValueError:
                    ok = False
                    break
            if not ok:
                continue
            for s, v in enumerate(vals):
                L = lens[s]
                if types[s] == 0:
                    a = np.zeros(L, np.float32)
                else:
                    a = np.zeros(L, np.int64)
                a[:min(len(v), L)] = v[:L]
                rows[s].append(a)
    return [
        np.stack(r) if r else np.zeros(
            (0, lens[s]), np.float32 if types[s] == 0 else np.int64)
        for s, r in enumerate(rows)
    ]


# ---------------------------------------------------------------------------
# Blocking reader queue (reference: framework/blocking_queue.h + the
# LoDTensorBlockingQueue bound at pybind.cc:591) — native bounded MPMC
# byte-buffer queue with a queue.Queue fallback.
# ---------------------------------------------------------------------------


class BlockingQueue:
    """Bounded blocking queue of PICKLED items — the serialized-batch /
    cross-process role of the reference's LoDTensorBlockingQueue (items
    must be picklable; in-process prefetch passes references through
    queue.Queue instead, see reader.py).  The C++ side releases the GIL
    while copying/waiting."""

    def __init__(self, capacity=64):
        import threading as _threading

        self._lib = get_lib()
        self._capacity = int(capacity)
        # peek+pop must be atomic per consumer (the C queue is MPMC but
        # the two-call read is not)
        self._pop_lock = _threading.Lock()
        self._closed = _threading.Event()
        if self._lib is not None:
            self._h = self._lib.ptq_create(self._capacity)
            self._q = None
        else:  # pure-python fallback with the same close semantics
            import queue

            self._h = None
            self._q = queue.Queue(maxsize=self._capacity)

    def push(self, obj):
        """False once the queue is closed."""
        import pickle
        import queue

        if self._h is not None:
            raw = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
            return bool(self._lib.ptq_push(self._h, raw, len(raw)))
        while not self._closed.is_set():
            try:
                self._q.put(obj, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def pop(self):
        """Next item, or None when closed and drained."""
        import pickle
        import queue

        if self._h is not None:
            with self._pop_lock:
                n = self._lib.ptq_peek_len(self._h)
                if n <= 0:
                    return None
                buf = ctypes.create_string_buffer(int(n))
                got = self._lib.ptq_pop(self._h, buf, int(n))
            if got <= 0:
                return None
            return pickle.loads(buf.raw[:got])
        while True:
            try:
                return self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed.is_set():
                    return None

    def size(self):
        if self._h is not None:
            return int(self._lib.ptq_size(self._h))
        return self._q.qsize()

    def close(self):
        self._closed.set()
        if self._h is not None:
            self._lib.ptq_close(self._h)

    def __del__(self):
        try:
            if self._h is not None:
                self._lib.ptq_close(self._h)
                self._lib.ptq_destroy(self._h)
                self._h = None
        except Exception:
            pass
