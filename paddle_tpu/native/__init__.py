"""Native (C++) runtime components, loaded via ctypes.

The reference implements its data-plane hot paths natively — RecordIO
(``paddle/fluid/recordio/``) and the MultiSlot DataFeed parser
(``paddle/fluid/framework/data_feed.cc``).  This package holds their
TPU-framework equivalents as a small C++ library (``src/*.cc``) built
on demand with g++ (no pybind11 in this image — plain C ABI + ctypes).

Every entry point has a pure-Python fallback so the framework works even
where a toolchain is unavailable; ``is_native()`` reports which path is
active.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_paddle_tpu_native.so")
_SOURCES = ["recordio.cc", "multislot.cc"]

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _build():
    srcs = [os.path.join(_HERE, "src", s) for s in _SOURCES]
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++14", "-pthread",
           "-o", _SO_PATH] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _newest_mtime(paths):
    return max(os.path.getmtime(p) for p in paths)


def get_lib():
    """Returns the loaded ctypes library, building it if needed; None if
    native support is unavailable."""
    global _lib, _build_attempted
    with _lib_lock:
        if _lib is not None:
            return _lib
        srcs = [os.path.join(_HERE, "src", s) for s in _SOURCES]
        stale = (not os.path.exists(_SO_PATH)
                 or os.path.getmtime(_SO_PATH) < _newest_mtime(srcs))
        if stale:
            if _build_attempted:
                return None
            _build_attempted = True
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        # signatures
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.c_int]
        lib.rio_write.restype = ctypes.c_int
        lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_long]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_next_size.restype = ctypes.c_long
        lib.rio_next_size.argtypes = [ctypes.c_void_p]
        lib.rio_next_copy.restype = ctypes.c_int
        lib.rio_next_copy.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.ms_parse_file.restype = ctypes.c_void_p
        lib.ms_parse_file.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_int]
        lib.ms_num_examples.restype = ctypes.c_long
        lib.ms_num_examples.argtypes = [ctypes.c_void_p]
        lib.ms_copy_slot.restype = ctypes.c_int
        lib.ms_copy_slot.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_void_p]
        lib.ms_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def is_native():
    return get_lib() is not None


# ---------------------------------------------------------------------------
# RecordIO
# ---------------------------------------------------------------------------

_RIO_MAGIC = 0x01020304  # reference header.h kMagicNumber


class RecordIOWriter:
    """Chunked record writer (reference recordio/writer.h)."""

    def __init__(self, path, max_chunk_records=1000, max_chunk_bytes=None):
        self._path = path
        self._max_records = max_chunk_records
        self._max_bytes = max_chunk_bytes or (32 << 20)
        lib = get_lib()
        self._lib = lib
        if lib is not None:
            self._h = lib.rio_writer_open(
                path.encode(), max_chunk_records, self._max_bytes)
            if not self._h:
                raise IOError("cannot open %s" % path)
        else:
            self._f = open(path, "wb")
            self._records = []
            self._pending = 0

    def write(self, data):
        if isinstance(data, str):
            data = data.encode()
        if self._lib is not None:
            if self._lib.rio_write(self._h, data, len(data)) != 0:
                raise IOError("recordio write failed")
            return
        self._records.append(bytes(data))
        self._pending += len(data)
        if (len(self._records) >= self._max_records
                or self._pending >= self._max_bytes):
            self._flush()

    def _flush(self):
        if not self._records:
            return
        import struct
        import zlib

        payload = b"".join(
            struct.pack("<I", len(r)) + r for r in self._records)
        header = struct.pack(
            "<IIIII", _RIO_MAGIC, len(self._records), 0,
            zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
        self._f.write(header + payload)
        self._records = []
        self._pending = 0

    def close(self):
        if self._lib is not None:
            if self._h:
                h, self._h = self._h, None  # C side frees even on error
                if self._lib.rio_writer_close(h) != 0:
                    raise IOError("recordio flush failed")
        elif self._f is not None:
            self._flush()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class RecordIOScanner:
    """Sequential record reader (reference recordio/scanner.h)."""

    def __init__(self, path):
        lib = get_lib()
        self._lib = lib
        if lib is not None:
            self._h = lib.rio_scanner_open(path.encode())
            if not self._h:
                raise IOError("cannot open %s" % path)
        else:
            self._f = open(path, "rb")
            self._chunk = []
            self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._lib is not None:
            size = self._lib.rio_next_size(self._h)
            if size == -1:
                raise StopIteration
            if size < 0:
                raise IOError("corrupt recordio chunk")
            buf = ctypes.create_string_buffer(int(size))
            if self._lib.rio_next_copy(self._h, buf) != 0:
                raise StopIteration
            return buf.raw[:size]
        # python fallback
        import struct
        import zlib

        while self._cursor >= len(self._chunk):
            head = self._f.read(20)
            if not head:
                raise StopIteration
            if len(head) < 20:  # truncated header
                raise IOError("corrupt recordio chunk")
            magic, num, comp, crc, size = struct.unpack("<IIIII", head)
            if magic != _RIO_MAGIC or comp != 0:
                raise IOError("corrupt recordio chunk")
            payload = self._f.read(size)
            if len(payload) != size or (zlib.crc32(payload)
                                        & 0xFFFFFFFF) != crc:
                raise IOError("corrupt recordio chunk")
            self._chunk = []
            pos = 0
            for _ in range(num):
                (ln,) = struct.unpack_from("<I", payload, pos)
                pos += 4
                self._chunk.append(payload[pos:pos + ln])
                pos += ln
            self._cursor = 0
        rec = self._chunk[self._cursor]
        self._cursor += 1
        return rec

    def close(self):
        if self._lib is not None:
            if self._h:
                self._lib.rio_scanner_close(self._h)
                self._h = None
        else:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# MultiSlot parser
# ---------------------------------------------------------------------------

def _wrap_u64(x):
    u = int(x) & 0xFFFFFFFFFFFFFFFF
    return u - (1 << 64) if u >= (1 << 63) else u


def parse_multislot_file(path, slot_types, slot_lens, threads=0):
    """Parse a MultiSlot text file into dense per-slot arrays.

    slot_types: 'float'/'uint64' (or 0/1) per slot; slot_lens: padded length
    per slot.  Returns list of np arrays [N, slot_len] (float32 / int64).
    """
    types = [0 if str(t).startswith(("f", "0")) else 1 for t in slot_types]
    lens = [int(l) for l in slot_lens]
    lib = get_lib()
    if lib is not None:
        n = len(types)
        ctypes_types = (ctypes.c_int * n)(*types)
        ctypes_lens = (ctypes.c_int * n)(*lens)
        h = lib.ms_parse_file(path.encode(), ctypes_types, ctypes_lens, n,
                              threads)
        if not h:
            raise IOError("cannot parse %s" % path)
        try:
            N = lib.ms_num_examples(h)
            out = []
            for s in range(n):
                if types[s] == 0:
                    arr = np.empty((N, lens[s]), np.float32)
                else:
                    arr = np.empty((N, lens[s]), np.int64)
                lib.ms_copy_slot(h, s, arr.ctypes.data_as(ctypes.c_void_p))
                out.append(arr)
            return out
        finally:
            lib.ms_free(h)
    # python fallback — skip-and-continue on malformed lines, matching the
    # native parser's error path
    rows = [[] for _ in types]
    with open(path) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            pos = 0
            vals = []
            ok = True
            for s in range(len(types)):
                if pos >= len(toks):
                    ok = False
                    break
                try:
                    cnt = int(toks[pos])
                except ValueError:
                    ok = False
                    break
                if cnt <= 0:  # reference enforces nonzero counts
                    ok = False
                    break
                pos += 1
                v = toks[pos:pos + cnt]
                if len(v) != cnt:
                    ok = False
                    break
                pos += cnt
                try:
                    if types[s] == 0:
                        vals.append([float(x) for x in v])
                    else:
                        # uint64 feasigns wrap two's-complement into int64,
                        # matching the native parser's C cast (jax has no
                        # uint64 on TPU; hash ids below 2^63 to avoid
                        # negative embedding rows)
                        vals.append([_wrap_u64(x) for x in v])
                except ValueError:
                    ok = False
                    break
            if not ok:
                continue
            for s, v in enumerate(vals):
                L = lens[s]
                if types[s] == 0:
                    a = np.zeros(L, np.float32)
                else:
                    a = np.zeros(L, np.int64)
                a[:min(len(v), L)] = v[:L]
                rows[s].append(a)
    return [
        np.stack(r) if r else np.zeros(
            (0, lens[s]), np.float32 if types[s] == 0 else np.int64)
        for s, r in enumerate(rows)
    ]
