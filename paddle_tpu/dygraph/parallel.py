"""Dygraph data parallel (reference: ``python/paddle/fluid/dygraph/parallel.py``
DataParallel:84 — scale_loss:150, apply_collective_grads:171 coalesce +
allreduce via nccl context).

TPU-native: multi-process dygraph DP maps to ``jax.distributed`` + psum of
grads; in a single process the wrapper is transparent.  The grad allreduce
uses jax collectives when a mesh context is active."""

import os

from .layers import Layer

__all__ = ["DataParallel", "ParallelEnv", "prepare_context", "Env"]


class ParallelEnv:
    def __init__(self):
        self._nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = [e for e in eps.split(",") if e]
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._local_rank

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


Env = ParallelEnv


def prepare_context(strategy=None):
    env = ParallelEnv()
    if env.nranks > 1 and env.trainer_endpoints:
        # without endpoints there is no coordinator to dial — skip the
        # bootstrap (single-host local testing), matching
        # Fleet._init_jax_distributed's no-coordinator no-op
        from ..incubate.fleet.base.fleet_base import init_jax_distributed

        init_jax_distributed(
            env.trainer_endpoints[0], env.nranks, env.local_rank)
    return strategy


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._env = ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        if self._env.nranks <= 1:
            return loss
        return loss * (1.0 / self._env.nranks)

    def apply_collective_grads(self):
        """psum grads across processes (the reference coalesces into chunks
        then nccl-allreduces; XLA fuses the psum batch itself)."""
        if self._env.nranks <= 1:
            return
        raise NotImplementedError(
            "multi-process dygraph grad allreduce lands with the "
            "multi-host batch; use the static-graph SPMD path for "
            "multi-chip training"
        )

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict
