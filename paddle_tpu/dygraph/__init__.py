"""Imperative (dygraph) front-end (reference:
``paddle/fluid/imperative/`` Tracer/VarBase + ``python/paddle/fluid/dygraph/``).

TPU-native eager: ops dispatch immediately through the same XLA-lowering
registry the static graph uses; a tape records them and backward replays
vjp-derived grad rules, so the op surface is identical in both modes."""

from .base import (guard, enabled, to_variable, enable_dygraph,
                   disable_dygraph, no_grad)
from .varbase import VarBase
from .layers import Layer
from . import nn
from .nn import (Linear, FC, Conv2D, Pool2D, BatchNorm, Embedding,
                 LayerNorm, Dropout, Conv3D, Conv2DTranspose,
                 Conv3DTranspose, GRUUnit, PRelu, BilinearTensorProduct,
                 SequenceConv, RowConv, GroupNorm, SpectralNorm, TreeConv,
                 NCE)
from .parallel import DataParallel, ParallelEnv, prepare_context
from .checkpoint import save_dygraph, load_dygraph
from .learning_rate_scheduler import (
    LearningRateDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
    InverseTimeDecay, PolynomialDecay, CosineDecay, NoamDecay)
from .tape import Tape as Tracer  # reference imperative.Tracer role


class BackwardStrategy:
    """reference dygraph.BackwardStrategy (pybind imperative.cc): the only
    knob, sort_sum_gradient, orders fan-in grad sums deterministically —
    our tape already accumulates in deterministic program order, so the
    flag is accepted and inert."""

    def __init__(self):
        self.sort_sum_gradient = False


__all__ = [
    "guard", "enabled", "to_variable", "enable_dygraph", "disable_dygraph",
    "no_grad", "VarBase", "Layer", "nn", "Linear", "FC", "Conv2D",
    "Pool2D", "BatchNorm", "Embedding", "LayerNorm", "Dropout",
    "DataParallel", "ParallelEnv", "prepare_context",
    "save_dygraph", "load_dygraph",
    "LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
    "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
    "CosineDecay", "NoamDecay", "Tracer", "BackwardStrategy",
]
