"""Imperative (dygraph) front-end (reference:
``paddle/fluid/imperative/`` Tracer/VarBase + ``python/paddle/fluid/dygraph/``).

TPU-native eager: ops dispatch immediately through the same XLA-lowering
registry the static graph uses; a tape records them and backward replays
vjp-derived grad rules, so the op surface is identical in both modes."""

from .base import (guard, enabled, to_variable, enable_dygraph,
                   disable_dygraph, no_grad)
from .varbase import VarBase
from .layers import Layer
from . import nn
from .nn import (Linear, FC, Conv2D, Pool2D, BatchNorm, Embedding,
                 LayerNorm, Dropout, Conv3D, Conv2DTranspose,
                 Conv3DTranspose, GRUUnit, PRelu, BilinearTensorProduct,
                 SequenceConv, RowConv, GroupNorm, SpectralNorm, TreeConv,
                 NCE)
from .parallel import DataParallel, ParallelEnv, prepare_context
from .checkpoint import save_dygraph, load_dygraph
from .learning_rate_scheduler import (
    LearningRateDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
    InverseTimeDecay, PolynomialDecay, CosineDecay, NoamDecay)
from .tape import Tape as Tracer  # reference imperative.Tracer role


class BackwardStrategy:
    """reference dygraph.BackwardStrategy (pybind imperative.cc): the only
    knob, sort_sum_gradient, orders fan-in grad sums deterministically —
    our tape already accumulates in deterministic program order, so the
    flag is accepted and inert."""

    def __init__(self):
        self.sort_sum_gradient = False


# reference dygraph/checkpoint.py exposes both naming generations
save_persistables = save_dygraph
load_persistables = load_dygraph


def start_gperf_profiler():
    """reference dygraph.start_gperf_profiler (gperftools hook): the
    profiling story here is paddle_tpu.profiler / jax XPlane."""
    from .. import profiler as _prof

    _prof.start_profiler("All")


def stop_gperf_profiler():
    from .. import profiler as _prof

    _prof.stop_profiler()


__all__ = [
    "guard", "enabled", "to_variable", "enable_dygraph", "disable_dygraph",
    "no_grad", "VarBase", "Layer", "nn", "Linear", "FC", "Conv2D",
    "Pool2D", "BatchNorm", "Embedding", "LayerNorm", "Dropout",
    "Conv3D", "Conv2DTranspose", "Conv3DTranspose", "GRUUnit", "PRelu",
    "BilinearTensorProduct", "SequenceConv", "RowConv", "GroupNorm",
    "SpectralNorm", "TreeConv", "NCE",
    "DataParallel", "ParallelEnv", "prepare_context",
    "save_dygraph", "load_dygraph",
    "LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
    "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
    "CosineDecay", "NoamDecay", "Tracer", "BackwardStrategy",
    "save_persistables", "load_persistables",
    "start_gperf_profiler", "stop_gperf_profiler",
]
