"""Imperative (dygraph) front-end (reference:
``paddle/fluid/imperative/`` + ``python/paddle/fluid/dygraph/``).

The eager tracer + Layer/nn module surface lands as its own batch (SURVEY.md
§7 stage 9); `guard`/`to_variable` plumbing is here so user scripts import
cleanly."""

from .base import guard, enabled, to_variable, enable_dygraph, disable_dygraph

__all__ = ["guard", "enabled", "to_variable", "enable_dygraph",
           "disable_dygraph"]
