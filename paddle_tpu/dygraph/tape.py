"""Eager autograd tape (reference: ``paddle/fluid/imperative/``:
``Tracer::Trace`` records OpBase/VarBase edges (tracer.cc:140), backward
walks them via ``VarBase::RunBackward`` (layer.h:260) + Engine).

TPU-native: eager ops ARE jax ops dispatched immediately; the tape records
(opdef, inputs, outputs, attrs) and backward replays each op's vjp-derived
grad rule — the same generic grad machinery the static graph uses, so every
registered op is dygraph-capable with zero extra code."""

from ..ops import registry as op_registry

__all__ = ["Tape", "current_tape", "push_tape", "pop_tape"]


class TapeEntry:
    __slots__ = ("opdef", "ins", "outs", "attrs", "op_id", "in_vars",
                 "out_vars")

    def __init__(self, opdef, ins, outs, attrs, op_id, in_vars, out_vars):
        self.opdef = opdef
        self.ins = ins          # {slot: [jnp values]}
        self.outs = outs        # {slot: [jnp values]}
        self.attrs = attrs
        self.op_id = op_id
        self.in_vars = in_vars  # {slot: [VarBase|None]}
        self.out_vars = out_vars


class Tape:
    def __init__(self):
        self.entries = []
        self.paused = False  # set by dygraph.no_grad()

    def record(self, entry):
        if not self.paused:
            self.entries.append(entry)

    # ---- reference imperative.Tracer API surface (tracer.h:41) ----
    def trace(self, entry):
        """reference Tracer.trace: record one executed op."""
        self.record(entry)

    trace_op = trace

    def trace_var(self, name, var):
        """reference Tracer.trace_var: vars are tracked via the entries'
        in/out VarBase references — nothing extra to do here."""
        return var

    def all_parameters(self):
        """reference Tracer.all_parameters: persistable VarBases seen on
        the tape."""
        seen, out = set(), []
        for e in self.entries:
            for vars_ in e.in_vars.values():
                for v in vars_:
                    if (v is not None and getattr(v, "persistable", False)
                            and id(v) not in seen):
                        seen.add(id(v))
                        out.append(v)
        return out

    def train_mode(self):
        self.paused = False

    def eval_mode(self):
        """no-grad evaluation: stop recording (dygraph.no_grad role)."""
        self.paused = True

    def backward(self, root_var, root_grad):
        import jax.numpy as jnp

        grads = {id(root_var): root_grad}

        ctx = op_registry.LoweringContext(mode="train")
        for e in reversed(self.entries):
            # collect available output grads for this entry
            out_grads = {}
            any_grad = False
            for slot, vars_ in e.out_vars.items():
                if slot in e.opdef.stateful_outputs:
                    continue
                gs = []
                for v in vars_:
                    g = grads.get(id(v)) if v is not None else None
                    gs.append(g)
                    any_grad = any_grad or g is not None
                out_grads[slot] = gs
            if not any_grad or e.opdef.no_grad:
                continue
            grad_def = op_registry.get_op_def(e.opdef.type + "_grad")
            gin = {}
            for slot, vals in e.ins.items():
                gin[slot] = vals
            for slot, vals in e.outs.items():
                gin[slot] = vals
            for slot, gs in out_grads.items():
                gin[slot + "@GRAD"] = gs
            attrs = dict(e.attrs)
            attrs["__fwd_op_id__"] = e.op_id
            result = op_registry.call_op(grad_def, ctx, gin, attrs,
                                         op_id=e.op_id)
            for slot, vars_ in e.in_vars.items():
                gvals = result.get(slot + "@GRAD")
                if gvals is None:
                    continue
                for v, g in zip(vars_, gvals):
                    if v is None or g is None or v.stop_gradient:
                        continue
                    prev = grads.get(id(v))
                    grads[id(v)] = g if prev is None else prev + g
        return grads


_tape_stack = []


def current_tape():
    return _tape_stack[-1] if _tape_stack else None


def push_tape(tape=None):
    t = tape or Tape()
    _tape_stack.append(t)
    return t


def pop_tape():
    return _tape_stack.pop()
