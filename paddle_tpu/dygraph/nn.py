"""Dygraph layers (reference: ``python/paddle/fluid/dygraph/nn.py`` —
Conv2D, FC, BatchNorm, Embedding, LayerNorm, Pool2D module classes)."""

import numpy as np

from .. import initializer as init_mod
from ..param_attr import ParamAttr
from .layers import Layer
from .varbase import VarBase, eager_op

__all__ = ["Linear", "FC", "Conv2D", "Pool2D", "BatchNorm", "Embedding",
           "LayerNorm", "Dropout", "Conv3D", "Conv2DTranspose",
           "Conv3DTranspose", "GRUUnit", "PRelu", "BilinearTensorProduct",
           "SequenceConv", "RowConv", "GroupNorm", "SpectralNorm",
           "TreeConv", "NCE"]


def _init_array(initializer, shape, dtype, rng):
    """Evaluate an initializer eagerly (dygraph params materialize at
    construction, not via a startup program)."""
    initializer = initializer or init_mod.XavierInitializer()
    if isinstance(initializer, init_mod.ConstantInitializer):
        return np.full(shape, initializer._value, dtype)
    if isinstance(initializer, init_mod.UniformInitializer):
        return rng.uniform(initializer._low, initializer._high,
                           shape).astype(dtype)
    if isinstance(initializer, init_mod.NormalInitializer):
        return (initializer._mean + initializer._std *
                rng.randn(*shape)).astype(dtype)
    if isinstance(initializer, init_mod.TruncatedNormalInitializer):
        v = rng.randn(*shape)
        v = np.clip(v, -2, 2)
        return (initializer._mean + initializer._std * v).astype(dtype)
    if isinstance(initializer, init_mod.XavierInitializer):
        fan_in, fan_out = _fans(shape)
        limit = np.sqrt(6.0 / (fan_in + fan_out))
        return rng.uniform(-limit, limit, shape).astype(dtype)
    if isinstance(initializer, init_mod.MSRAInitializer):
        fan_in, _ = _fans(shape)
        limit = np.sqrt(6.0 / fan_in)
        return rng.uniform(-limit, limit, shape).astype(dtype)
    if isinstance(initializer, init_mod.NumpyArrayInitializer):
        return np.asarray(initializer._value, dtype)
    raise NotImplementedError(type(initializer))


def _fans(shape):
    if len(shape) < 2:
        return (shape[0] if shape else 1,) * 2
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


_param_rng = np.random.RandomState(20190701)


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        p = ParamAttr._to_attr(param_attr)
        self.weight = self.create_parameter(
            [input_dim, output_dim], dtype,
            _init_array(p.initializer, (input_dim, output_dim), dtype,
                        _param_rng),
        )
        self._act = act
        b = ParamAttr._to_attr(bias_attr)
        if b is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [output_dim], dtype,
                _init_array(b.initializer or init_mod.Constant(0.0),
                            (output_dim,), dtype, _param_rng),
            )

    def forward(self, x):
        out = eager_op("mul", {"X": [x], "Y": [self.weight]},
                       {"x_num_col_dims": 1, "y_num_col_dims": 1})[0]
        if self.bias is not None:
            out = eager_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 1})[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class FC(Linear):
    """Old-style FC (reference dygraph/nn.py FC) — alias of Linear with
    size-first signature."""

    def __init__(self, name_scope=None, size=None, input_dim=None,
                 param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        if input_dim is None:
            raise ValueError("FC requires input_dim on TPU (static shapes)")
        super().__init__(input_dim, size, param_attr, bias_attr, act, dtype)


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        fs = filter_size if isinstance(filter_size, (list, tuple)) else (
            filter_size, filter_size)
        shape = (num_filters, num_channels // (groups or 1)) + tuple(fs)
        p = ParamAttr._to_attr(param_attr)
        fan_in = shape[1] * shape[2] * shape[3]
        default = init_mod.NormalInitializer(0.0, (2.0 / fan_in) ** 0.5)
        self.weight = self.create_parameter(
            list(shape), dtype,
            _init_array(p.initializer or default, shape, dtype, _param_rng),
        )
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [num_filters], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (num_filters,), dtype, _param_rng),
        )
        self._attrs = {
            "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 2 if isinstance(dilation, int) else list(dilation),
            "groups": groups or 1,
        }
        self._act = act

    def forward(self, x):
        out = eager_op("conv2d", {"Input": [x], "Filter": [self.weight]},
                       self._attrs)[0]
        if self.bias is not None:
            out = eager_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 1})[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, ceil_mode=False,
                 exclusive=True):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride] * 2 if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding] * 2 if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, x):
        return eager_op("pool2d", {"X": [x]}, self._attrs)[0]


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW"):
        super().__init__()
        c = (num_channels,)
        self.weight = self.create_parameter(
            [num_channels], "float32",
            _init_array(init_mod.Constant(1.0), c, "float32", _param_rng),
        )
        self.bias = self.create_parameter(
            [num_channels], "float32",
            _init_array(init_mod.Constant(0.0), c, "float32", _param_rng),
        )
        self._mean = VarBase(np.zeros(c, "float32"), "bn.mean",
                             stop_gradient=True, persistable=True)
        self._variance = VarBase(np.ones(c, "float32"), "bn.var",
                                 stop_gradient=True, persistable=True)
        self._attrs = {
            "momentum": momentum, "epsilon": epsilon,
            "data_layout": data_layout, "is_test": is_test,
        }
        self._act = act

    def forward(self, x):
        outs = eager_op(
            "batch_norm",
            {"X": [x], "Scale": [self.weight], "Bias": [self.bias],
             "Mean": [self._mean], "Variance": [self._variance]},
            dict(self._attrs, is_test=self._attrs["is_test"] or
                 not self.training),
        )
        y, mean_out, var_out = outs[0], outs[1], outs[2]
        self._mean.set_value(mean_out.value)
        self._variance.set_value(var_out.value)
        if self._act:
            y = eager_op(self._act, {"X": [y]})[0]
        return y


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None,
                 param_attr=None, dtype="float32"):
        super().__init__()
        p = ParamAttr._to_attr(param_attr)
        default = init_mod.UniformInitializer(-0.05, 0.05)
        self.weight = self.create_parameter(
            list(size), dtype,
            _init_array(p.initializer or default, tuple(size), dtype,
                        _param_rng),
        )
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, ids):
        return eager_op(
            "lookup_table", {"W": [self.weight], "Ids": [ids]},
            {"padding_idx": self._padding_idx},
        )[0]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, dtype="float32"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self.weight = self.create_parameter(
            [n], "float32", np.ones(n, "float32"))
        self.bias = self.create_parameter(
            [n], "float32", np.zeros(n, "float32"))
        self._eps = epsilon

    def forward(self, x):
        return eager_op(
            "layer_norm",
            {"X": [x], "Scale": [self.weight], "Bias": [self.bias]},
            {"begin_norm_axis": len(x.shape) - 1, "epsilon": self._eps},
        )[0]


class Dropout(Layer):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        return eager_op(
            "dropout", {"X": [x]},
            {"dropout_prob": self._p, "is_test": not self.training,
             "dropout_implementation": "upscale_in_train"},
        )[0]


class Conv3D(Layer):
    """reference dygraph/nn.py Conv3D → conv3d op (NCDHW)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        fs = (filter_size if isinstance(filter_size, (list, tuple))
              else (filter_size,) * 3)
        shape = (num_filters, num_channels // (groups or 1)) + tuple(fs)
        p = ParamAttr._to_attr(param_attr)
        fan_in = shape[1] * shape[2] * shape[3] * shape[4]
        default = init_mod.NormalInitializer(0.0, (2.0 / fan_in) ** 0.5)
        self.weight = self.create_parameter(
            list(shape), dtype,
            _init_array(p.initializer or default, shape, dtype, _param_rng))
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [num_filters], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (num_filters,), dtype, _param_rng))
        def trip(v):
            return [v] * 3 if isinstance(v, int) else list(v)
        self._attrs = {"strides": trip(stride), "paddings": trip(padding),
                       "dilations": trip(dilation), "groups": groups or 1}
        self._act = act

    def forward(self, x):
        out = eager_op("conv3d", {"Input": [x], "Filter": [self.weight]},
                       self._attrs)[0]
        if self.bias is not None:
            out = eager_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 1})[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class Conv2DTranspose(Layer):
    """reference dygraph/nn.py Conv2DTranspose → conv2d_transpose op."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        fs = (filter_size if isinstance(filter_size, (list, tuple))
              else (filter_size,) * 2)
        shape = (num_channels, num_filters // (groups or 1)) + tuple(fs)
        p = ParamAttr._to_attr(param_attr)
        default = init_mod.XavierInitializer()
        self.weight = self.create_parameter(
            list(shape), dtype,
            _init_array(p.initializer or default, shape, dtype, _param_rng))
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [num_filters], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (num_filters,), dtype, _param_rng))
        def pair(v):
            return [v] * 2 if isinstance(v, int) else list(v)
        self._attrs = {"strides": pair(stride), "paddings": pair(padding),
                       "dilations": pair(dilation), "groups": groups or 1}
        self._act = act

    def forward(self, x):
        out = eager_op("conv2d_transpose",
                       {"Input": [x], "Filter": [self.weight]},
                       self._attrs)[0]
        if self.bias is not None:
            out = eager_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 1})[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class Conv3DTranspose(Layer):
    """reference dygraph/nn.py Conv3DTranspose → conv3d_transpose op."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        fs = (filter_size if isinstance(filter_size, (list, tuple))
              else (filter_size,) * 3)
        shape = (num_channels, num_filters) + tuple(fs)
        p = ParamAttr._to_attr(param_attr)
        self.weight = self.create_parameter(
            list(shape), dtype,
            _init_array(p.initializer or init_mod.XavierInitializer(),
                        shape, dtype, _param_rng))
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [num_filters], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (num_filters,), dtype, _param_rng))
        def trip(v):
            return [v] * 3 if isinstance(v, int) else list(v)
        self._attrs = {"strides": trip(stride), "paddings": trip(padding),
                       "dilations": trip(dilation)}
        self._act = act

    def forward(self, x):
        out = eager_op("conv3d_transpose",
                       {"Input": [x], "Filter": [self.weight]},
                       self._attrs)[0]
        if self.bias is not None:
            out = eager_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 1})[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class GRUUnit(Layer):
    """reference dygraph/nn.py GRUUnit → gru_unit op; size = 3*D."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        d = size // 3
        p = ParamAttr._to_attr(param_attr)
        self.weight = self.create_parameter(
            [d, 3 * d], dtype,
            _init_array(p.initializer, (d, 3 * d), dtype, _param_rng))
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [1, 3 * d], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (1, 3 * d), dtype, _param_rng))
        self._attrs = {"activation": activation,
                       "gate_activation": gate_activation,
                       "origin_mode": origin_mode}

    def forward(self, input, hidden):
        ins = {"Input": [input], "HiddenPrev": [hidden],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        # declared slot order: Gate, ResetHiddenPrev, Hidden
        gate, rhp, hid = eager_op("gru_unit", ins, self._attrs)
        return hid, rhp, gate


class PRelu(Layer):
    """reference dygraph/nn.py PRelu → prelu op."""

    def __init__(self, mode="all", channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__()
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [channel or 1]
        else:
            shape = list(input_shape or [1])
        p = ParamAttr._to_attr(param_attr)
        self.weight = self.create_parameter(
            shape, dtype,
            _init_array(p.initializer or init_mod.Constant(0.25),
                        tuple(shape), dtype, _param_rng))
        self._mode = mode

    def forward(self, x):
        return eager_op("prelu", {"X": [x], "Alpha": [self.weight]},
                        {"mode": self._mode})[0]


class BilinearTensorProduct(Layer):
    """reference dygraph/nn.py BilinearTensorProduct."""

    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__()
        p = ParamAttr._to_attr(param_attr)
        shape = (output_dim, input1_dim, input2_dim)
        self.weight = self.create_parameter(
            list(shape), dtype,
            _init_array(p.initializer, shape, dtype, _param_rng))
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [1, output_dim], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (1, output_dim), dtype, _param_rng))
        self._act = act

    def forward(self, x, y):
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = eager_op("bilinear_tensor_product", ins, {})[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class SequenceConv(Layer):
    """reference dygraph/nn.py SequenceConv → sequence_conv op (padded
    [B,T,D] + optional lengths)."""

    def __init__(self, name_scope=None, num_filters=1, filter_size=3,
                 filter_stride=1, padding=None, bias_attr=None,
                 param_attr=None, act=None, input_dim=None,
                 dtype="float32"):
        super().__init__()
        if input_dim is None:
            raise ValueError(
                "SequenceConv requires input_dim on TPU (static shapes)")
        p = ParamAttr._to_attr(param_attr)
        shape = (filter_size * input_dim, num_filters)
        self.weight = self.create_parameter(
            list(shape), dtype,
            _init_array(p.initializer, shape, dtype, _param_rng))
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [num_filters], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (num_filters,), dtype, _param_rng))
        self._attrs = {"contextLength": int(filter_size),
                       "contextStart": -int(filter_size // 2),
                       "contextStride": int(filter_stride)}
        self._act = act

    def forward(self, x, seq_len=None):
        ins = {"X": [x], "Filter": [self.weight]}
        if seq_len is not None:
            ins["SeqLen"] = [seq_len]
        out = eager_op("sequence_conv", ins, self._attrs)[0]
        if self.bias is not None:
            out = eager_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 2})[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class RowConv(Layer):
    """reference dygraph/nn.py RowConv → row_conv op."""

    def __init__(self, name_scope=None, future_ctx_size=2,
                 param_attr=None, act=None, input_dim=None,
                 dtype="float32"):
        super().__init__()
        if input_dim is None:
            raise ValueError(
                "RowConv requires input_dim on TPU (static shapes)")
        p = ParamAttr._to_attr(param_attr)
        shape = (future_ctx_size + 1, input_dim)
        self.weight = self.create_parameter(
            list(shape), dtype,
            _init_array(p.initializer, shape, dtype, _param_rng))
        self._act = act

    def forward(self, x):
        out = eager_op("row_conv",
                       {"X": [x], "Filter": [self.weight]}, {})[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class GroupNorm(Layer):
    """reference dygraph/nn.py GroupNorm → group_norm op."""

    def __init__(self, channels=None, groups=1, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None,
                 dtype="float32", name_scope=None):
        super().__init__()
        p = ParamAttr._to_attr(param_attr)
        self.weight = None if p is False else self.create_parameter(
            [channels], dtype,
            _init_array(p.initializer or init_mod.Constant(1.0),
                        (channels,), dtype, _param_rng))
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [channels], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (channels,), dtype, _param_rng))
        self._attrs = {"groups": int(groups), "epsilon": float(epsilon)}
        self._act = act

    def forward(self, x):
        ins = {"X": [x]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        y, _, _ = eager_op("group_norm", ins, self._attrs)
        if self._act:
            y = eager_op(self._act, {"X": [y]})[0]
        return y


class SpectralNorm(Layer):
    """reference dygraph/nn.py SpectralNorm → spectral_norm op."""

    def __init__(self, weight_shape=None, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32", name_scope=None):
        super().__init__()
        h = weight_shape[dim]
        import math as _math

        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.u = self.create_parameter(
            [h], dtype,
            _init_array(init_mod.NormalInitializer(0.0, 1.0), (h,), dtype,
                        _param_rng))
        self.v = self.create_parameter(
            [w], dtype,
            _init_array(init_mod.NormalInitializer(0.0, 1.0), (w,), dtype,
                        _param_rng))
        self._attrs = {"dim": int(dim), "power_iters": int(power_iters),
                       "eps": float(eps)}

    def forward(self, weight):
        return eager_op(
            "spectral_norm",
            {"Weight": [weight], "U": [self.u], "V": [self.v]},
            self._attrs)[0]


class TreeConv(Layer):
    """reference dygraph/nn.py TreeConv → tree_conv op."""

    def __init__(self, feature_size=None, output_size=1, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 name_scope=None, dtype="float32"):
        super().__init__()
        p = ParamAttr._to_attr(param_attr)
        shape = (feature_size, output_size, 3)
        self.weight = self.create_parameter(
            list(shape), dtype,
            _init_array(p.initializer, shape, dtype, _param_rng))
        self._attrs = {"max_depth": int(max_depth)}
        self._act = act

    def forward(self, nodes_vector, edge_set):
        out = eager_op(
            "tree_conv",
            {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
             "Filter": [self.weight]}, self._attrs)[0]
        if self._act:
            out = eager_op(self._act, {"X": [out]})[0]
        return out


class NCE(Layer):
    """reference dygraph/nn.py NCE → nce op."""

    def __init__(self, num_total_classes, dim=None, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler="uniform", custom_dist=None, seed=0,
                 is_sparse=False, dtype="float32", name_scope=None):
        super().__init__()
        if dim is None:
            raise ValueError("NCE requires dim on TPU (static shapes)")
        p = ParamAttr._to_attr(param_attr)
        self.weight = self.create_parameter(
            [num_total_classes, dim], dtype,
            _init_array(p.initializer, (num_total_classes, dim), dtype,
                        _param_rng))
        b = ParamAttr._to_attr(bias_attr)
        self.bias = None if b is False else self.create_parameter(
            [num_total_classes, 1], dtype,
            _init_array(b.initializer or init_mod.Constant(0.0),
                        (num_total_classes, 1), dtype, _param_rng))
        self._attrs = {
            "num_total_classes": int(num_total_classes),
            "num_neg_samples": int(num_neg_samples),
            "sampler": {"uniform": 0, "log_uniform": 1}.get(sampler, 0),
            "seed": seed,
        }

    def forward(self, input, label):
        ins = {"Input": [input], "Label": [label],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        cost, _, _ = eager_op("nce", ins, self._attrs)
        return cost
