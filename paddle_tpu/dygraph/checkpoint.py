"""Dygraph checkpoint (reference:
``python/paddle/fluid/dygraph/checkpoint.py`` save/load state dicts)."""

import os

import numpy as np

__all__ = ["save_dygraph", "load_dygraph", "save_persistables",
           "load_persistables"]


def save_dygraph(state_dict, model_path):
    arrays = {k: np.asarray(v) for k, v in state_dict.items()}
    np.savez(model_path + ".pdparams.npz", **arrays)


def load_dygraph(model_path):
    path = model_path + ".pdparams.npz"
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    data = np.load(path)
    return {k: data[k] for k in data.files}, None


def save_persistables(model_dict, dirname="save_dir"):
    os.makedirs(dirname, exist_ok=True)
    save_dygraph(model_dict, os.path.join(dirname, "params"))


def load_persistables(dirname="save_dir"):
    state, _ = load_dygraph(os.path.join(dirname, "params"))
    return state
