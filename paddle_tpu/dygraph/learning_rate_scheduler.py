"""Dygraph learning-rate decay objects (reference:
``python/paddle/fluid/dygraph/learning_rate_scheduler.py`` — eager-mode
counterparts of the graph-op schedules in
``layers/learning_rate_scheduler.py``).

An instance is passed as ``learning_rate`` to an optimizer; each
minimize() consumes one step's value (``step()``)."""

import math

__all__ = [
    "LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
    "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
    "CosineDecay", "NoamDecay",
]


class LearningRateDecay:
    def __init__(self, begin=0, step=1):
        self.step_num = int(begin)
        self.step_size = int(step)

    def value(self):
        raise NotImplementedError

    def step(self):
        v = self.value()
        self.step_num += self.step_size
        return v

    def create_lr_var(self, lr):
        """reference LearningRateDecay.create_lr_var wraps the float in a
        [1] float32 variable; eager values are jnp arrays here."""
        import jax.numpy as jnp

        return jnp.asarray([float(lr)], jnp.float32)

    # reference API: calling the object yields the current value
    def __call__(self):
        return self.value()


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin, step=1):
        super().__init__(begin, step)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def value(self):
        for b, v in zip(self.boundaries, self.values):
            if self.step_num < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def value(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.lr * math.exp(-self.decay_rate * div)


class ExponentialDecay(NaturalExpDecay):
    def value(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.lr * (self.decay_rate ** div)


class InverseTimeDecay(NaturalExpDecay):
    def value(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.lr / (1.0 + self.decay_rate * div)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.decay_steps = decay_steps
        self.end_lr = end_learning_rate
        self.power = power
        self.cycle = cycle

    def value(self):
        n = self.step_num
        steps = self.decay_steps
        if self.cycle:
            div = math.ceil(n / steps) if n > 0 else 1.0
            steps = steps * max(div, 1.0)
        else:
            n = min(n, steps)
        return ((self.lr - self.end_lr)
                * (1 - n / steps) ** self.power + self.end_lr)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def value(self):
        cur_epoch = math.floor(self.step_num / self.step_each_epoch)
        return (self.lr * 0.5
                * (math.cos(cur_epoch * math.pi / self.epochs) + 1))


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1):
        super().__init__(begin, step)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def value(self):
        n = max(self.step_num, 1)
        a = n ** -0.5
        b = (self.warmup_steps ** -1.5) * n
        return (self.d_model ** -0.5) * min(a, b)
