"""Dygraph mode plumbing (reference: ``python/paddle/fluid/dygraph/base.py``).

Eager mode is jax's default op-by-op dispatch; ops are recorded on a tape
for autograd (tape.py)."""

import contextlib

import numpy as np

from .. import framework
from .tape import push_tape, pop_tape

__all__ = ["guard", "enabled", "to_variable", "enable_dygraph",
           "disable_dygraph", "no_grad"]


def enabled():
    return framework.in_dygraph_mode()


def enable_dygraph(place=None):
    framework._dygraph_tracer_ = push_tape()


def disable_dygraph():
    """Exit the innermost dygraph scope, restoring the enclosing one (so
    nested guards compose and no tape leaks on the stack)."""
    from .tape import current_tape

    if framework.in_dygraph_mode():
        pop_tape()
    framework._dygraph_tracer_ = current_tape()


@contextlib.contextmanager
def guard(place=None):
    enable_dygraph(place)
    try:
        yield
    finally:
        disable_dygraph()


@contextlib.contextmanager
def no_grad():
    """Suspend gradient RECORDING while staying in dygraph mode
    (reference dygraph.no_grad): eager dispatch still works, the tape just
    ignores ops executed in the scope."""
    tape = framework._dygraph_tracer_
    prev = getattr(tape, "paused", False) if tape is not None else False
    if tape is not None:
        tape.paused = True
    try:
        yield
    finally:
        if tape is not None:
            tape.paused = prev


def to_variable(value, block=None, name=None):
    from .varbase import VarBase

    if not framework.in_dygraph_mode():
        raise RuntimeError("to_variable requires dygraph mode (use guard())")
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name, stop_gradient=True)
