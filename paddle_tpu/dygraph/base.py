"""Dygraph mode plumbing (reference: ``python/paddle/fluid/dygraph/base.py``).

On TPU, eager mode is simply jax's default op-by-op dispatch; the full
Layer/autograd surface lands with the dygraph batch."""

import contextlib

from .. import framework

__all__ = ["guard", "enabled", "to_variable", "enable_dygraph",
           "disable_dygraph"]


def enabled():
    return framework.in_dygraph_mode()


def enable_dygraph(place=None):
    framework._dygraph_tracer_ = object()  # marker; eager dispatch is jax's


def disable_dygraph():
    framework._dygraph_tracer_ = None


@contextlib.contextmanager
def guard(place=None):
    enable_dygraph(place)
    try:
        yield
    finally:
        disable_dygraph()


def to_variable(value, block=None, name=None):
    import jax.numpy as jnp

    if not framework.in_dygraph_mode():
        raise RuntimeError("to_variable requires dygraph mode (use guard())")
    return jnp.asarray(value)
