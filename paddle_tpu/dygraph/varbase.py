"""Eager variable (reference: ``paddle/fluid/imperative/layer.h:133``
VarBase) — a jnp array + grad slot + tape bookkeeping."""

import numpy as np

from ..ops import registry as op_registry
from .tape import current_tape, TapeEntry

__all__ = ["VarBase", "eager_op", "to_variable_value"]

_eager_op_counter = [0]


class VarBase:
    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False, trainable=True):
        import jax.numpy as jnp

        self._value = jnp.asarray(value)
        self.name = name or "eager_var"
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self._grad = None

    # ---- reference VarBase surface ----
    def numpy(self):
        return np.asarray(self._value)

    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return str(self._value.dtype)

    @property
    def gradient_value(self):
        return self._grad

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self):
        self._grad = None

    def set_value(self, value):
        import jax.numpy as jnp

        self._value = jnp.asarray(value)

    def detach(self):
        return VarBase(self._value, self.name + ".detached",
                       stop_gradient=True)

    def backward(self, retain_graph=False):
        import jax.numpy as jnp

        tape = current_tape()
        if tape is None:
            raise RuntimeError(
                "backward() outside dygraph.guard() — no tape is recording"
            )
        grads = tape.backward(self, jnp.ones_like(self._value))
        # deposit grads on every VarBase seen by the tape
        seen = {}
        for e in tape.entries:
            for vars_ in list(e.in_vars.values()) + list(e.out_vars.values()):
                for v in vars_:
                    if v is not None:
                        seen[id(v)] = v
        seen[id(self)] = self
        for vid, g in grads.items():
            v = seen.get(vid)
            if v is not None and not v.stop_gradient:
                v._grad = g if v._grad is None else v._grad + g
        if not retain_graph:
            tape.entries.clear()

    def __repr__(self):
        return "VarBase(%s, shape=%s)" % (self.name, self.shape)

    # ---- operator sugar (eager) ----
    def _binary(self, other, op_type, reverse=False):
        o = other if isinstance(other, VarBase) else VarBase(
            np.asarray(other, dtype=self.numpy().dtype), stop_gradient=True
        )
        a, b = (o, self) if reverse else (self, o)
        return eager_op(op_type, {"X": [a], "Y": [b]}, {"axis": -1})[0]

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")


def to_variable_value(v):
    if isinstance(v, VarBase):
        return v._value
    return v


def eager_op(op_type, in_vars, attrs=None, n_outs=None):
    """Dispatch one op eagerly and record it on the tape.  `in_vars`:
    {slot: [VarBase|value|None]}.  Returns list of output VarBases in the
    opdef's declared slot order."""
    opdef = op_registry.get_op_def(op_type)
    attrs = dict(attrs or {})
    _eager_op_counter[0] += 1
    op_id = _eager_op_counter[0]

    ins_vals = {}
    in_vb = {}
    for slot, vs in in_vars.items():
        vals, vbs = [], []
        for v in vs:
            if isinstance(v, VarBase):
                vals.append(v._value)
                vbs.append(v)
            else:
                vals.append(v)
                vbs.append(None)
        ins_vals[slot] = vals
        in_vb[slot] = vbs

    ctx = op_registry.LoweringContext(mode="train")
    outs = op_registry.call_op(opdef, ctx, ins_vals, attrs, op_id=op_id)

    out_vb = {}
    flat_out = []
    for slot, dup in opdef.outputs:
        vals = outs.get(slot)
        if vals is None:
            out_vb[slot] = []
            continue
        vbs = []
        for v in vals:
            vb = VarBase(v, name="%s.%s" % (op_type, slot)) \
                if not isinstance(v, dict) else VarBase(
                    np.zeros(1), stop_gradient=True)
            vbs.append(vb)
            flat_out.append(vb)
        out_vb[slot] = vbs

    tape = current_tape()
    if tape is not None and not opdef.no_grad:
        tape.record(TapeEntry(opdef, ins_vals, outs, attrs, op_id, in_vb,
                              out_vb))
    return flat_out
