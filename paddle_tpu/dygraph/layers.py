"""Dygraph Layer base (reference: ``python/paddle/fluid/dygraph/layers.py``)."""

from collections import OrderedDict

import numpy as np

from .varbase import VarBase

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self.training = True

    def create_parameter(self, shape, dtype, value):
        p = VarBase(np.asarray(value, dtype), persistable=True,
                    stop_gradient=False)
        p.trainable = True
        return p

    # attribute tracking of params / sublayers
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        if isinstance(value, VarBase) and value.persistable and params is not None:
            params[name] = value
        elif isinstance(value, Layer) and subs is not None:
            subs[name] = value
        object.__setattr__(self, name, value)

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    # ---- state dict (reference dygraph/checkpoint.py) ----
    def state_dict(self, prefix=""):
        out = OrderedDict()
        for name, p in self._parameters.items():
            out[prefix + name] = p.numpy()
        for lname, l in self._sub_layers.items():
            out.update(l.state_dict(prefix + lname + "."))
        return out

    def set_dict(self, state, prefix=""):
        for name, p in self._parameters.items():
            key = prefix + name
            if key in state:
                p.set_value(state[key])
        for lname, l in self._sub_layers.items():
            l.set_dict(state, prefix + lname + ".")

    load_dict = set_dict
