"""Dygraph Layer base (reference: ``python/paddle/fluid/dygraph/layers.py``)."""

from collections import OrderedDict

import numpy as np

from .varbase import VarBase

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self.training = True
        self._full_name = name_scope or self.__class__.__name__.lower()

    def full_name(self):
        """reference Layer.full_name: the layer's name scope."""
        return self._full_name

    def add_parameter(self, name, parameter):
        """reference Layer.add_parameter: register + return (validates
        like the reference instead of silently dropping non-parameters
        from parameters()/state_dict())."""
        if not (isinstance(parameter, VarBase) and parameter.persistable):
            raise TypeError(
                "add_parameter expects a persistable VarBase (a "
                "parameter); got %r — create it via create_parameter or "
                "VarBase(..., persistable=True)" % (parameter,))
        setattr(self, name, parameter)
        return parameter

    def add_sublayer(self, name, sublayer):
        """reference Layer.add_sublayer: register + return."""
        setattr(self, name, sublayer)
        return sublayer

    def create_variable(self, name=None, persistable=None, dtype="float32",
                        type=None):
        """reference Layer.create_variable: a non-parameter buffer."""
        v = VarBase(np.zeros((1,), dtype),
                    persistable=bool(persistable), stop_gradient=True)
        return v

    def backward(self, *inputs):
        """reference Layer.backward raises — grads flow through the tape
        via loss.backward(), not per-layer hooks."""
        raise ValueError("Layer.backward is not implemented; call "
                         "backward() on the loss VarBase instead")

    def create_parameter(self, shape, dtype, value):
        from .. import unique_name

        # unique per-process names (deterministic under the same model
        # construction order) key the optimizer's accumulator state, so
        # Optimizer.load can restore it across processes
        p = VarBase(np.asarray(value, dtype),
                    name=unique_name.generate("eager_param"),
                    persistable=True, stop_gradient=False)
        p.trainable = True
        return p

    # attribute tracking of params / sublayers
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        if isinstance(value, VarBase) and value.persistable and params is not None:
            params[name] = value
        elif isinstance(value, Layer) and subs is not None:
            subs[name] = value
        object.__setattr__(self, name, value)

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    # ---- state dict (reference dygraph/checkpoint.py) ----
    def state_dict(self, prefix=""):
        out = OrderedDict()
        for name, p in self._parameters.items():
            out[prefix + name] = p.numpy()
        for lname, l in self._sub_layers.items():
            out.update(l.state_dict(prefix + lname + "."))
        return out

    def set_dict(self, state, prefix=""):
        for name, p in self._parameters.items():
            key = prefix + name
            if key in state:
                p.set_value(state[key])
        for lname, l in self._sub_layers.items():
            l.set_dict(state, prefix + lname + ".")

    load_dict = set_dict
