"""Initializers emitted as startup-program ops (reference:
``python/paddle/fluid/initializer.py`` — each __call__ appends a
fill_constant / gaussian_random / uniform_random op to the startup block)."""

import math

import numpy as np

__all__ = [
    "Initializer",
    "Constant",
    "ConstantInitializer",
    "Uniform",
    "UniformInitializer",
    "Normal",
    "NormalInitializer",
    "TruncatedNormal",
    "TruncatedNormalInitializer",
    "Xavier",
    "XavierInitializer",
    "MSRA",
    "MSRAInitializer",
    "Bilinear",
    "BilinearInitializer",
    "NumpyArrayInitializer",
    "set_global_initializer",
]

_global_weight_initializer_ = None
_global_bias_initializer_ = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_initializer_, _global_bias_initializer_
    _global_weight_initializer_ = weight_init
    _global_bias_initializer_ = bias_init


def _global_weight_initializer():
    return _global_weight_initializer_


def _global_bias_initializer():
    return _global_bias_initializer_


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def _compute_fans(self, var):
        shape = var.shape
        if not shape or len(shape) == 0:
            fan_in = fan_out = 1
        elif len(shape) == 1:
            fan_in = fan_out = shape[0]
        elif len(shape) == 2:
            fan_in, fan_out = shape[0], shape[1]
        else:
            receptive = int(np.prod(shape[2:]))
            fan_in = shape[1] * receptive
            fan_out = shape[0] * receptive
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "value": float(self._value),
            },
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high, self._seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": self._low,
                "max": self._high,
                "seed": self._seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self._mean,
                "std": self._std,
                "seed": self._seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self._mean,
                "std": self._std,
                "seed": self._seed,
            },
        )


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform, self._fan_in, self._fan_out, self._seed = (
            uniform, fan_in, fan_out, seed,
        )

    def __call__(self, var, block):
        f_in, f_out = self._compute_fans(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        fan_out = f_out if self._fan_out is None else self._fan_out
        if self._uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform, self._fan_in, self._seed = uniform, fan_in, seed

    def __call__(self, var, block):
        f_in, _ = self._compute_fans(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        if self._uniform:
            limit = math.sqrt(6.0 / fan_in)
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = math.sqrt(2.0 / fan_in)
        return NormalInitializer(0.0, std, self._seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling filter init (reference initializer.py Bilinear)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer expects rank-4 filter")
        weight = np.zeros(shape, dtype="float32")
        size = shape[3]
        factor = (size + 1) // 2
        center = factor - 1 if size % 2 == 1 else factor - 0.5
        og = np.ogrid[:size, :size]
        filt = (1 - abs(og[0] - center) / factor) * (
            1 - abs(og[1] - center) / factor
        )
        weight[range(shape[0]), range(shape[1]), :, :] = filt
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(self._value.shape),
                "dtype": var.dtype,
                "values": self._value,
            },
        )


# reference short aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def force_init_on_cpu():
    """reference initializer.force_init_on_cpu flag: initializers always
    run host-side here (startup program on CPU feeds device buffers), so
    this is constant False for API parity."""
    return False


import contextlib as _contextlib  # noqa: E402


@_contextlib.contextmanager
def init_on_cpu():
    """reference initializer.init_on_cpu context: a no-op — startup
    initialization already happens host-side and XLA stages the results."""
    yield


__all__ += ["force_init_on_cpu", "init_on_cpu"]
