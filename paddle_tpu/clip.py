"""Gradient clipping appended as graph ops (reference:
``python/paddle/fluid/clip.py``)."""

from .framework import default_main_program
from . import unique_name

__all__ = [
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
    "ErrorClipByValue",
]


class BaseErrorClipAttr:
    pass


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


def error_clip_callback(block, context):
    pass


class BaseGradientClipAttr:
    def _process(self, params_grads):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _process(self, params_grads):
        return params_grads


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _clip_one(self, block, grad):
        out = block.create_var(
            name=unique_name.generate(grad.name + ".clip"),
            shape=grad.shape, dtype=grad.dtype,
        )
        block.append_op(
            type="clip", inputs={"X": [grad]}, outputs={"Out": [out]},
            attrs={"min": self.min, "max": self.max, "op_role": "optimize"},
        )
        return out

    def _process(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, self._clip_one(g.block, g)))
        return out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            block = g.block
            o = block.create_var(
                name=unique_name.generate(g.name + ".clipnorm"),
                shape=g.shape, dtype=g.dtype,
            )
            block.append_op(
                type="clip_by_norm", inputs={"X": [g]},
                outputs={"Out": [o]},
                attrs={"max_norm": self.clip_norm, "op_role": "optimize"},
            )
            out.append((p, o))
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """scale = clip_norm / max(global_norm, clip_norm), applied to every
    grad (reference clip.py GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        live = [(p, g) for p, g in params_grads if g is not None]
        if not live:
            return params_grads
        block = live[0][1].block
        sq_norms = []
        for _, g in live:
            sq = block.create_var(
                name=unique_name.generate(g.name + ".sq"),
                shape=[1], dtype="float32",
            )
            block.append_op(
                type="squared_l2_norm", inputs={"X": [g]},
                outputs={"Out": [sq]}, attrs={"op_role": "optimize"},
            )
            sq_norms.append(sq)
        total = block.create_var(
            name=unique_name.generate("global_norm_sq"), shape=[1],
            dtype="float32",
        )
        block.append_op(
            type="sum", inputs={"X": sq_norms}, outputs={"Out": [total]},
            attrs={"op_role": "optimize"},
        )
        gnorm = block.create_var(
            name=unique_name.generate("global_norm"), shape=[1],
            dtype="float32",
        )
        block.append_op(
            type="sqrt", inputs={"X": [total]}, outputs={"Out": [gnorm]},
            attrs={"op_role": "optimize"},
        )
        # denom = max(gnorm, clip_norm); scale = clip_norm / denom
        clipc = block.create_var(
            name=unique_name.generate("clip_norm_const"), shape=[1],
            dtype="float32",
        )
        block.append_op(
            type="fill_constant", outputs={"Out": [clipc]},
            attrs={"shape": [1], "dtype": "float32", "value": self.clip_norm,
                   "op_role": "optimize"},
        )
        denom = block.create_var(
            name=unique_name.generate("clip_denom"), shape=[1],
            dtype="float32",
        )
        block.append_op(
            type="elementwise_max", inputs={"X": [gnorm], "Y": [clipc]},
            outputs={"Out": [denom]}, attrs={"op_role": "optimize"},
        )
        scale = block.create_var(
            name=unique_name.generate("clip_scale"), shape=[1],
            dtype="float32",
        )
        block.append_op(
            type="elementwise_div", inputs={"X": [clipc], "Y": [denom]},
            outputs={"Out": [scale]}, attrs={"op_role": "optimize"},
        )
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            o = g.block.create_var(
                name=unique_name.generate(g.name + ".gclip"),
                shape=g.shape, dtype=g.dtype,
            )
            g.block.append_op(
                type="elementwise_mul", inputs={"X": [g], "Y": [scale]},
                outputs={"Out": [o]}, attrs={"op_role": "optimize"},
            )
            out.append((p, o))
        return out


_clip_attr = {}


import contextlib


@contextlib.contextmanager
def per_call_gradient_clip(program, clip):
    """Temporarily register ``clip`` for ``program`` (the minimize
    ``grad_clip=`` argument), restoring any persistent
    ``set_gradient_clip`` registration on exit.  The single owner of the
    register/restore dance — both minimize implementations use it."""
    if clip is None:
        yield
        return
    pid = id(program)
    prev = _clip_attr.get(pid)
    _clip_attr[pid] = clip
    try:
        yield
    finally:
        if prev is None:
            _clip_attr.pop(pid, None)
        else:
            _clip_attr[pid] = prev


def set_gradient_clip(clip, param_list=None, program=None):
    if program is None:
        program = default_main_program()
    _clip_attr[id(program)] = clip


def append_gradient_clip_ops(params_grads):
    if not params_grads:
        return params_grads
    program = params_grads[0][0].block.program
    clip = _clip_attr.get(id(program))
    # per-param clip attrs win (reference clip.py:333)
    per_param = [
        getattr(p, "gradient_clip_attr", None) for p, _ in params_grads
    ]
    if clip is None and not any(per_param):
        return params_grads
    if clip is not None:
        return clip._process(params_grads)
    out = []
    for (p, g), attr in zip(params_grads, per_param):
        if attr is None or g is None:
            out.append((p, g))
        else:
            out.extend(attr._process([(p, g)]))
    return out
