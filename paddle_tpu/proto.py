"""Program serialization (reference: protobuf ``framework.proto:184``; here a
JSON-shaped dict with the same nesting ProgramDesc ⊃ BlockDesc ⊃
{VarDesc, OpDesc} so saved models round-trip)."""

import json

import numpy as np

from .framework import Program, Parameter

FORMAT_VERSION = 1


def _attr_to_jsonable(v):
    if isinstance(v, np.ndarray):
        return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def _attr_from_jsonable(v):
    if isinstance(v, dict) and "__ndarray__" in v:
        return np.asarray(v["__ndarray__"], dtype=v["dtype"])
    return v


def program_to_dict(program):
    blocks = []
    for b in program.blocks:
        vars_ = []
        for v in b.vars.values():
            vars_.append({
                "name": v.name,
                "shape": list(v.shape) if v.shape is not None else None,
                "dtype": v.dtype,
                "lod_level": v.lod_level,
                "persistable": v.persistable,
                "stop_gradient": v.stop_gradient,
                "is_data": v.is_data,
                "is_parameter": isinstance(v, Parameter),
                "trainable": getattr(v, "trainable", False),
                "need_check_feed": getattr(v, "need_check_feed", False),
                "feed_hint": getattr(v, "feed_hint", None),
            })
        ops = []
        for op in b.ops:
            ops.append({
                "type": op.type,
                "inputs": op.inputs,
                "outputs": op.outputs,
                "attrs": {k: _attr_to_jsonable(v) for k, v in op.attrs.items()},
            })
        blocks.append({
            "idx": b.idx,
            "parent_idx": b.parent_idx,
            "vars": vars_,
            "ops": ops,
        })
    return {"version": FORMAT_VERSION, "blocks": blocks,
            "random_seed": program.random_seed}


def program_from_dict(d):
    from .framework import Block, Operator, Variable

    p = Program()
    p.random_seed = d.get("random_seed", 0)
    p.blocks = []
    for bd in d["blocks"]:
        b = Block(p, bd["idx"], bd.get("parent_idx", -1))
        p.blocks.append(b)
    for bd, b in zip(d["blocks"], p.blocks):
        for vd in bd["vars"]:
            if vd.get("is_parameter"):
                v = Parameter(
                    b, shape=vd["shape"], dtype=vd["dtype"], name=vd["name"],
                    trainable=vd.get("trainable", True),
                )
            else:
                v = Variable(
                    b, name=vd["name"], shape=vd["shape"], dtype=vd["dtype"],
                    lod_level=vd.get("lod_level", 0),
                    persistable=vd.get("persistable", False),
                    stop_gradient=vd.get("stop_gradient", False),
                    is_data=vd.get("is_data", False),
                    # pre-existing saves lack the key; data vars are always
                    # built with the feed check on, so fall back to is_data
                    need_check_feed=vd.get(
                        "need_check_feed", vd.get("is_data", False)),
                )
                v.feed_hint = vd.get("feed_hint")
            b.vars[v.name] = v
        for od in bd["ops"]:
            op = Operator(
                b, od["type"],
                {k: list(v) for k, v in od["inputs"].items()},
                {k: list(v) for k, v in od["outputs"].items()},
                {k: _attr_from_jsonable(v) for k, v in od["attrs"].items()},
            )
            b.ops.append(op)
    p.current_block_idx = 0
    p._bump_version()
    return p


def save_program(program, path):
    with open(path, "w") as f:
        json.dump(program_to_dict(program), f)


def load_program(path):
    with open(path) as f:
        return program_from_dict(json.load(f))
