"""Graph-building metric evaluators (reference:
``python/paddle/fluid/evaluator.py`` — deprecated in favor of
fluid.metrics, kept for API parity: each Evaluator appends its metric ops
plus persistable accumulator state, with reset/eval run through the
executor)."""

import numpy as np

from . import unique_name
from .framework import Program, Variable, default_main_program, program_guard
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance"]


class Evaluator:
    """Base evaluator (reference evaluator.py:Evaluator): subclasses
    create accumulator states updated by in-graph ops; ``reset`` zeroes
    them, ``eval`` computes the final metric on the host."""

    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        if reset_program is None:
            reset_program = Program()
        with program_guard(main_program=reset_program):
            for var in self.states:
                zeros = self.helper.main_program.current_block()
                reset_program.global_block().create_var(
                    name=var.name, shape=var.shape, dtype=var.dtype,
                    persistable=True)
                reset_program.global_block().append_op(
                    type="fill_constant",
                    outputs={"Out": [var.name]},
                    attrs={"shape": list(var.shape), "dtype": var.dtype,
                           "value": 0.0})
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def _create_state(self, suffix, dtype, shape):
        state = self.helper.main_program.current_block().create_var(
            name="_".join([unique_name.generate(self.helper.name), suffix]),
            persistable=True, dtype=dtype, shape=list(shape))
        state.stop_gradient = True
        self.helper.set_variable_initializer(
            state, ConstantInitializer(0.0))
        self.states.append(state)
        return state


class ChunkEvaluator(Evaluator):
    """Accumulating chunk F1 (reference evaluator.py:ChunkEvaluator):
    sums num_infer/num_label/num_correct chunks across batches."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, seq_length=None):
        super().__init__("chunk_eval")
        from .layers import nn_extra2 as _l

        main_program = self.helper.main_program
        self.num_infer_chunks = self._create_state(
            "num_infer_chunks", "int64", (1,))
        self.num_label_chunks = self._create_state(
            "num_label_chunks", "int64", (1,))
        self.num_correct_chunks = self._create_state(
            "num_correct_chunks", "int64", (1,))
        (precision, recall, f1, num_infer, num_label,
         num_correct) = _l.chunk_eval(
            input, label, chunk_scheme, num_chunk_types,
            excluded_chunk_types, seq_length)
        block = main_program.current_block()
        for state, delta in ((self.num_infer_chunks, num_infer),
                             (self.num_label_chunks, num_label),
                             (self.num_correct_chunks, num_correct)):
            block.append_op(
                type="sum", inputs={"X": [state, delta]},
                outputs={"Out": [state]})
        self.metrics.extend([precision, recall, f1])

    def eval(self, executor, eval_program=None):
        from .executor import global_scope
        from .pipeline import host_values

        # one batched device→host sync for all three accumulators —
        # per-var np.asarray would serialize the async dispatch queue
        # three times per eval
        scope = global_scope()
        ni, nl, nc = (
            float(a.reshape(-1)[0]) for a in host_values([
                scope.get(self.num_infer_chunks.name),
                scope.get(self.num_label_chunks.name),
                scope.get(self.num_correct_chunks.name),
            ]))
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return np.array([precision]), np.array([recall]), np.array([f1])


class EditDistance(Evaluator):
    """Accumulating average edit distance (reference
    evaluator.py:EditDistance)."""

    def __init__(self, input, label, ignored_tokens=None,
                 input_length=None, label_length=None):
        super().__init__("edit_distance")
        from .layers import nn_extra2 as _l

        self.total_distance = self._create_state(
            "total_distance", "float32", (1,))
        self.seq_num = self._create_state("seq_num", "int64", (1,))
        distances, seq_num = _l.edit_distance(
            input, label, normalized=False,
            ignored_tokens=ignored_tokens,
            input_length=input_length, label_length=label_length)
        from .layers import nn as _nn

        batch_total = _nn.reduce_sum(distances)
        block = self.helper.main_program.current_block()
        block.append_op(type="sum",
                        inputs={"X": [self.total_distance, batch_total]},
                        outputs={"Out": [self.total_distance]})
        block.append_op(type="sum", inputs={"X": [self.seq_num, seq_num]},
                        outputs={"Out": [self.seq_num]})
        self.metrics.append(distances)

    def eval(self, executor, eval_program=None):
        from .executor import global_scope
        from .pipeline import host_values

        scope = global_scope()
        total, n = (
            float(a.reshape(-1)[0]) for a in host_values([
                scope.get(self.total_distance.name),
                scope.get(self.seq_num.name),
            ]))
        return np.array([total / n if n else 0.0])
