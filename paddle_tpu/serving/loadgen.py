"""Built-in load generator for the predictor server.

Paced open-loop submission (fixed offered QPS, round-robin across
tenants), exact percentile computation from the recorded per-request
latencies, and a JSON-able report — what ``python -m
paddle_tpu.tools.serve --loadgen`` and ``bench.py --child serving``
both run.
"""

import time

import numpy as np

from .server import QueueFullError

__all__ = ["make_feed_sampler", "percentile", "run_load"]


def make_feed_sampler(predictor, rows=1, rng=None, int_high=1):
    """Build a feed sampler from the program's declared data vars:
    float feeds get standard-normal noise, integer feeds uniform ids in
    ``[0, int_high)`` (keeps embedding lookups in-vocab).  The leading
    ``-1`` batch dim becomes ``rows``.  Returns a zero-arg callable
    producing a fresh name→array feed."""
    rng = np.random.RandomState(0) if rng is None else rng
    program = predictor.program
    block = program.global_block()
    specs = []
    for name in predictor.get_input_names():
        var = block.var(name)
        shape = [rows if int(d) == -1 else int(d) for d in var.shape]
        if not shape:
            shape = [rows]
        dtype = str(getattr(var, "dtype", "float32") or "float32")
        specs.append((name, tuple(shape), dtype))

    def sample():
        feed = {}
        for name, shape, dtype in specs:
            if "int" in dtype:
                feed[name] = rng.randint(
                    0, max(int_high, 1), size=shape).astype(dtype)
            else:
                # bfloat16 has no numpy dtype — feed f32, the lowering
                # casts on device
                feed[name] = rng.standard_normal(shape).astype(
                    dtype if dtype.startswith("float") else "float32")
        return feed

    return sample


def percentile(latencies, q):
    """Exact percentile (nearest-rank) of a latency list; None when
    empty."""
    if not latencies:
        return None
    xs = sorted(latencies)
    k = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


def run_load(server, samplers, qps=100.0, requests=100, sla_ms=None,
             burst=False):
    """Drive ``server`` with generated traffic and report latency and
    throughput.

    ``samplers``: ``{tenant: zero-arg feed factory}``.  Open-loop pacing
    at ``qps`` offered load (``burst=True`` submits everything at once —
    the saturation-throughput mode bench's A/B arm uses).  Rejected
    submits (backpressure) are counted, not retried.

    Returns a JSON-able report: counts, ``p50_ms``/``p99_ms``/mean
    latency, measured ``qps`` (completions over the submit→last-complete
    span), shed/reject counts and per-tenant breakdown.
    """
    tenants = list(samplers)
    period = 0.0 if burst or qps <= 0 else 1.0 / qps
    pending = []
    rejected = 0
    t0 = time.time()
    next_at = t0
    for i in range(requests):
        tenant = tenants[i % len(tenants)]
        if period:
            delay = next_at - time.time()
            if delay > 0:
                time.sleep(delay)
            next_at += period
        try:
            pending.append(server.submit(
                tenant, samplers[tenant](),
                request_id="%s-%d" % (tenant, i), sla_ms=sla_ms))
        except QueueFullError:
            rejected += 1
    lat, shed, failed = [], 0, 0
    per_tenant = {t: {"completed": 0, "shed": 0, "latencies": []}
                  for t in tenants}
    for req in pending:
        try:
            req.result(timeout=120.0)
            lat.append(req.latency_ms)
            per_tenant[req.tenant]["completed"] += 1
            per_tenant[req.tenant]["latencies"].append(req.latency_ms)
        except Exception as exc:  # noqa: BLE001
            if type(exc).__name__ == "DeadlineExceededError":
                shed += 1
                per_tenant[req.tenant]["shed"] += 1
            else:
                failed += 1
    wall = max(time.time() - t0, 1e-9)
    report = {
        "requests": requests,
        "completed": len(lat),
        "shed": shed,
        "rejected": rejected,
        "failed": failed,
        "offered_qps": None if burst else qps,
        "qps": len(lat) / wall,
        "duration_s": round(wall, 4),
        "p50_ms": percentile(lat, 50),
        "p99_ms": percentile(lat, 99),
        "mean_ms": (sum(lat) / len(lat)) if lat else None,
        "shed_rate": shed / float(requests) if requests else 0.0,
        "tenants": {
            t: {
                "completed": d["completed"],
                "shed": d["shed"],
                "p50_ms": percentile(d["latencies"], 50),
                "p99_ms": percentile(d["latencies"], 99),
            } for t, d in per_tenant.items()
        },
    }
    return report
