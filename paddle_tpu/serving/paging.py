"""Paged KV-cache management (the vLLM-style allocator half of the
ISSUE-19 tentpole).

The slot-ring cache (`[slots, H, Tmax, Dh]`) reserves ``Tmax`` rows per
stream for its whole lifetime — a request that generates 30 tokens into
a 512-deep cache idles 94% of its reservation, and mixed generation
lengths fragment HBM until the slot count, not the chip, caps the
concurrent streams.  Here HBM is carved into fixed-size blocks
(``[num_blocks, H, block_len, Dh]``): a request owns exactly
``ceil(tokens / block_len)`` blocks, named by its **block table** (an
int32 ``[max_blocks]`` row, ``-1`` = unmapped), and the free-list hands
blocks out and takes them back as requests are admitted and retired.

Determinism: the free-list is LIFO and every mutation happens on the
engine scheduler thread (or under its condition lock), so a seeded
admit/generate/retire schedule replays bit-exactly — the property the
``tests`` churn sweep pins (never double-assigns, never leaks).

Kill switch: ``PADDLE_TPU_PAGED_KV=0`` makes :func:`paged_kv_enabled`
false and the :class:`~paddle_tpu.serving.decode.DecodeEngine` keeps
its slot-ring path bit-exactly.

Block size: ``PADDLE_TPU_PAGED_BLOCK_LEN`` → the autotune ``decode``
family's measured ``block_len`` winner for this head_dim → the hand-set
default (ops/pallas/paged_flash_decode.py) — the same env → cache →
default precedence every tuned knob in the tree follows.
"""

import os

import numpy as np

__all__ = ["BlockAllocator", "KVPoolExhausted", "blocks_needed",
           "build_block_table", "paged_kv_enabled"]

PAGED_KV_ENV = "PADDLE_TPU_PAGED_KV"


def paged_kv_enabled():
    """The tentpole kill switch: ``PADDLE_TPU_PAGED_KV=0`` restores the
    slot-ring cache path bit-exactly (default: paged on)."""
    return os.environ.get(PAGED_KV_ENV, "1").strip() != "0"


def blocks_needed(tokens, block_len):
    """Blocks a request owning ``tokens`` cache rows must hold."""
    tokens = int(tokens)
    if tokens <= 0:
        return 0
    return -(-tokens // int(block_len))


def build_block_table(blocks, max_blocks):
    """An int32 ``[max_blocks]`` table row: owned block ids first,
    ``-1`` padding after (the paged ops drop writes routed to ``-1``
    and the attention mask never reads past the owned depth)."""
    table = np.full((int(max_blocks),), -1, dtype="int32")
    if blocks:
        table[:len(blocks)] = np.asarray(list(blocks), dtype="int32")
    return table


class KVPoolExhausted(RuntimeError):
    """An allocation asked for more blocks than the free-list holds —
    the engine treats this as backpressure (the request stays queued
    until retirements return blocks), never as partial allocation."""


class BlockAllocator:
    """LIFO free-list over ``num_blocks`` fixed-size KV blocks.

    Invariants (the property-test contract):

    * a block id is owned by at most one holder at a time — ``allocate``
      never hands out an id that has not been ``free``\\ d back;
    * conservation — ``num_free + sum(live allocations) == num_blocks``
      at every point in any schedule;
    * ``free`` rejects double-frees and foreign ids loudly instead of
      corrupting the list.
    """

    __slots__ = ("num_blocks", "block_len", "_free", "_live")

    def __init__(self, num_blocks, block_len):
        self.num_blocks = int(num_blocks)
        self.block_len = int(block_len)
        if self.num_blocks < 1:
            raise ValueError("num_blocks must be >= 1, got %d"
                             % self.num_blocks)
        if self.block_len < 1:
            raise ValueError("block_len must be >= 1, got %d"
                             % self.block_len)
        # LIFO: block 0 on top so fresh pools allocate 0,1,2,... — the
        # deterministic order the churn property test replays
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._live = set()

    @property
    def num_free(self):
        return len(self._free)

    def can_allocate(self, n):
        return int(n) <= len(self._free)

    def allocate(self, n):
        """Pop ``n`` block ids; all-or-nothing (raises
        :class:`KVPoolExhausted` without touching the list when the
        pool is short)."""
        n = int(n)
        if n < 0:
            raise ValueError("cannot allocate %d blocks" % n)
        if n > len(self._free):
            raise KVPoolExhausted(
                "KV pool exhausted: asked for %d block(s), %d free of "
                "%d" % (n, len(self._free), self.num_blocks))
        got = [self._free.pop() for _ in range(n)]
        self._live.update(got)
        return got

    def free(self, blocks):
        """Return a request's blocks to the pool (retirement)."""
        blocks = list(blocks)
        for b in blocks:
            b = int(b)
            if b not in self._live:
                raise ValueError(
                    "freeing block %d which is not live (double-free "
                    "or foreign id; %d live, %d free)"
                    % (b, len(self._live), len(self._free)))
        for b in blocks:
            self._live.discard(int(b))
            self._free.append(int(b))

    def __repr__(self):
        return "BlockAllocator(%d/%d free, block_len=%d)" % (
            len(self._free), self.num_blocks, self.block_len)
