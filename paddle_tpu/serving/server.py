"""Continuous-batching multi-tenant predictor server.

The pipeline is::

      submit() ──► per-tenant queue ──► bucketer ──► in-flight dispatcher
      (bounded,      (deadline-sorted,   (pad to a     (run_async window of
       rejects)       sheds late work)    fixed set)    max_in_flight, then
                                                        fetch + slice out)

``submit`` enqueues a :class:`Request` (validated against the program's
``need_check_feed`` marks immediately — a bad shape is attributed to the
offending request id, never surfaced K steps later as a raw jit error).
A dispatcher thread coalesces queued requests of one tenant into a
padded shape bucket (:mod:`paddle_tpu.serving.buckets` — bounding the
jit cache), dispatches through the predictor's async path
(``run_async``, the same zero-sync dispatch ``run_batches`` streams
through) and keeps up to ``max_in_flight`` dispatched batches' fetch
handles un-synced; the oldest batch is materialized with ONE batched
sync and each request receives its own rows.

Guarantees enforced at construction (``verify=True``):

* **Scope isolation** — co-resident tenants' programs are proven
  scope-disjoint by the PR-10 ``coresident`` proof
  (:func:`~paddle_tpu.static_analysis.concurrency.prove_scope_isolation`);
  a written overlap is a hard :class:`VerifyError` before the server
  accepts any traffic.  Shared read-only names are allowed and recorded
  in ``placement_diags``.
* **Zero-sync hot loop** — each tenant's program is stamped
  ``_serving_hot_loop`` (strict-sync promotion) and must pass
  :func:`~paddle_tpu.static_analysis.concurrency.verify_async_hot_path`
  at the configured in-flight depth; the per-tenant
  :class:`ZeroSyncCertificate` is kept in ``certificates``.

Scheduling: per-tenant round-robin (fairness), per-request SLA
deadlines with priority eviction (a request that can no longer meet its
deadline — ``now + EMA(batch service time) > deadline`` — is shed at
batch formation rather than poisoning the batch), and backpressure (a
bounded queue that rejects with :class:`QueueFullError`).

Failure contract: a batch that fails dispatch or materialization fails
only its own requests; if the dispatcher THREAD itself dies, every
pending request is failed with :class:`DispatcherCrashedError`, the
crash is journaled urgent (``dispatcher-died``), and the server stays
dead — no client ever blocks forever on :meth:`Request.result`.
"""

import itertools
import threading
import time

import numpy as np

from ..executor import _check_feed_shapes
from ..observability import runtime as _obs
from ..observability import tracing as _tr
from ..static_analysis.diagnostics import Severity, format_diagnostics
from .buckets import ShapeBuckets

__all__ = [
    "DeadlineExceededError",
    "DispatcherCrashedError",
    "PredictorServer",
    "QueueFullError",
    "Request",
    "ServerClosedError",
    "ServingError",
]


class ServingError(RuntimeError):
    pass


class QueueFullError(ServingError):
    """Backpressure: the bounded request queue rejected the submit."""


class ServerClosedError(ServingError):
    pass


class DeadlineExceededError(ServingError):
    """The request was shed: it could no longer meet its SLA deadline."""


class DispatcherCrashedError(ServingError):
    """The dispatcher thread died outside the per-batch guards.

    Every pending request (queued and in-flight) is failed with this
    error — a client blocked in :meth:`Request.result` gets a typed
    verdict, never a silent hang — and the server is dead: subsequent
    :meth:`PredictorServer.submit` calls raise it too.  The crash is
    journaled urgent as ``dispatcher-died``."""


class Request:
    """One enqueued inference request (a mini-batch of ``rows`` rows).

    ``result(timeout)`` blocks until completion and returns the list of
    fetch outputs sliced to this request's rows, or raises the error the
    request was failed with (shed, validation, executor error).
    """

    __slots__ = ("id", "tenant", "feed", "rows", "deadline", "enqueue_ts",
                 "sig", "seq", "_event", "_outputs", "_error",
                 "latency_ms", "queue_wait_ms", "span", "_qspan")

    def __init__(self, rid, tenant, feed, rows, deadline, sig, seq):
        self.id = rid
        self.tenant = tenant
        self.feed = feed
        self.rows = rows
        self.deadline = deadline
        self.enqueue_ts = time.time()
        self.sig = sig
        self.seq = seq
        self._event = threading.Event()
        self._outputs = None
        self._error = None
        self.latency_ms = None
        self.queue_wait_ms = None
        # request-lifecycle spans: span covers enqueue→respond and is
        # ended by whichever thread completes/fails the request; _qspan
        # covers enqueue→batch-formation (or shed)
        self.span = _tr.NULL_SPAN
        self._qspan = _tr.NULL_SPAN

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("request %r not completed within %ss"
                               % (self.id, timeout))
        if self._error is not None:
            raise self._error
        return self._outputs

    # dispatcher-side completion
    def _complete(self, outputs):
        self._outputs = outputs
        self.latency_ms = (time.time() - self.enqueue_ts) * 1000.0
        self.span.set_attr("latency_ms", round(self.latency_ms, 3))
        self.span.end("ok")
        self._event.set()

    def _fail(self, exc):
        self._error = exc
        self.latency_ms = (time.time() - self.enqueue_ts) * 1000.0
        status = "%s:%s" % (
            "shed" if isinstance(exc, DeadlineExceededError)
            else "crash" if isinstance(exc, DispatcherCrashedError)
            else "error", type(exc).__name__)
        self._qspan.end(status)
        self.span.end(status)
        self._event.set()

    def __repr__(self):
        return "Request(id=%r, tenant=%r, rows=%d)" % (
            self.id, self.tenant, self.rows)


class _Tenant:
    __slots__ = ("name", "predictor", "queue", "est_ms", "feed_names")

    def __init__(self, name, predictor):
        self.name = name
        self.predictor = predictor
        self.queue = []          # Requests, ordered at batch formation
        self.est_ms = None       # EMA of batch service (dispatch→fetch)
        get = getattr(predictor, "get_input_names", None)
        self.feed_names = list(get()) if get is not None else None


class _InFlight:
    __slots__ = ("tenant", "requests", "offsets", "bucket", "handles",
                 "dispatch_ts", "span")

    def __init__(self, tenant, requests, offsets, bucket, handles,
                 dispatch_ts, span=_tr.NULL_SPAN):
        self.tenant = tenant
        self.requests = requests
        self.offsets = offsets
        self.bucket = bucket
        self.handles = handles
        self.dispatch_ts = dispatch_ts
        self.span = span  # serving.batch, ends after the batched sync


class PredictorServer:
    """Continuous-batching server over one or more
    :class:`~paddle_tpu.inference.AnalysisPredictor`\\ s.

    ``tenants``: ``{name: predictor}`` (or a single predictor, served as
    tenant ``"default"``).  Each tenant keeps its own predictor (own
    Scope, own jit cache); the scope-overlap proof gates their
    co-residency in this process.
    """

    #: EMA smoothing for the per-tenant batch-service-time estimate
    EST_ALPHA = 0.3

    def __init__(self, tenants, max_in_flight=2, sla_ms=None,
                 queue_cap=256, buckets=None, bucket_cap=None,
                 verify=True, auto_start=True):
        from .decode import DecodeEngine

        if hasattr(tenants, "run_async") or hasattr(tenants, "program"):
            tenants = {"default": tenants}
        if not tenants:
            raise ValueError("PredictorServer needs at least one tenant")
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1, got %d"
                             % max_in_flight)
        # decode tenants run their own slot scheduler (continuous
        # batching over KV-cache blocks) instead of the padded-batch
        # dispatcher; they still go through the co-residency proof and
        # the zero-sync stamp below
        self._engines = {name: t for name, t in tenants.items()
                         if isinstance(t, DecodeEngine)}
        self._tenants = {name: _Tenant(name, pred)
                         for name, pred in tenants.items()
                         if not isinstance(pred, DecodeEngine)}
        self._order = list(self._tenants)   # round-robin order
        self._rr = 0
        self._max_in_flight = int(max_in_flight)
        self._sla_ms = sla_ms
        self._queue_cap = int(queue_cap)
        self.buckets = (buckets if isinstance(buckets, ShapeBuckets)
                        else ShapeBuckets(buckets, cap=bucket_cap))
        self._seq = itertools.count()
        self._cond = threading.Condition()
        self._running = False
        self._closed = False
        self._crashed = None
        self._thread = None
        self._inflight = []          # owned by the dispatcher thread
        self.dispatch_log = []       # (tenant, bucket, rows) — bounded
        self.stats_lock = threading.Lock()
        self._counts = {"submitted": 0, "completed": 0, "shed": 0,
                        "rejected": 0, "failed": 0}
        self._first_dispatch_ts = None
        self._last_complete_ts = None
        self.placement_diags = ()
        self.certificates = {}
        if verify:
            self._verify_placement()
        self._stamp_hot_loop(verify)
        if auto_start:
            self.start()

    # ------------------------------------------------------------------
    # construction-time gates
    # ------------------------------------------------------------------

    def _verify_placement(self):
        """The PR-10 ``coresident`` scope-overlap proof: a written
        overlap between tenant programs is a hard error before any
        traffic; shared read-only names are advisory."""
        from ..static_analysis.concurrency import prove_scope_isolation
        from ..static_analysis.verifier import VerifyError

        programs = [t.predictor.program for t in self._tenants.values()]
        labels = list(self._tenants)
        # decode engines co-reside too: their step program names the
        # resident caches, so a cache-name collision between tenants is
        # caught here.  A disaggregated engine contributes ALL its
        # resident program families (prefill runs on its own thread
        # against the same scope) — the pool overlap between them is a
        # declared KV-block handoff, not an accidental collision
        for name, eng in self._engines.items():
            co = getattr(eng, "coresident_programs", None)
            if co is not None:
                for label, prog, _targets in co():
                    programs.append(prog)
                    labels.append(label)
            else:
                programs.append(eng.program)
                labels.append(name)
        if len(programs) < 2:
            return
        _fp, diags = prove_scope_isolation(programs, labels=labels)
        self.placement_diags = tuple(diags)
        errors = [d for d in diags if d.severity >= Severity.ERROR]
        if errors:
            raise VerifyError(format_diagnostics(
                diags,
                header="multi-tenant placement rejected "
                       "(scope-overlap proof failed)"))

    def _stamp_hot_loop(self, verify):
        """Stamp every tenant program as the serving hot loop (strict
        zero-sync promotion) at this in-flight depth, verify the async
        path, and keep the per-tenant zero-sync certificate."""
        from ..static_analysis.concurrency import (certify_zero_sync,
                                                   verify_async_hot_path)

        entries = []
        for t in self._tenants.values():
            prog = t.predictor.program
            targets = []
            get = getattr(t.predictor, "get_output_names", None)
            if get is not None:
                targets = list(get())
            entries.append((t.name, prog, targets))
        # a decode engine's hot loop is its step program — the one the
        # slot scheduler re-runs every generated token.  Disaggregated
        # engines also run their prefill programs concurrently, so
        # those get stamped + certified under "name.prefillL" labels
        for name, eng in self._engines.items():
            co = getattr(eng, "coresident_programs", None)
            if co is not None:
                entries.extend(co())
            else:
                entries.append((name, eng.program,
                                list(eng.get_output_names())))
        for name, prog, targets in entries:
            prog._serving_hot_loop = True
            prog._max_in_flight = max(
                self._max_in_flight,
                int(getattr(prog, "_max_in_flight", 1) or 1))
            if verify:
                verify_async_hot_path(prog, targets=targets,
                                      max_in_flight=self._max_in_flight,
                                      label="serving:%s" % name)
            self.certificates[name] = certify_zero_sync(
                prog, targets=targets, label="serving:%s" % name,
                max_in_flight=self._max_in_flight)

    # ------------------------------------------------------------------
    # client side
    # ------------------------------------------------------------------

    def _as_feed(self, tenant, inputs):
        as_feed = getattr(tenant.predictor, "_as_feed", None)
        if as_feed is not None:
            return as_feed(inputs)
        if isinstance(inputs, dict):
            return dict(inputs)
        names = tenant.feed_names or []
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if len(inputs) != len(names):
            raise ValueError("expected %d inputs (%s), got %d"
                             % (len(names), names, len(inputs)))
        return dict(zip(names, inputs))

    def _validate(self, rid, tenant, feed):
        """Enqueue-time validation: every fed array must be batch-leading
        with one consistent row count <= the largest bucket, and must
        satisfy the program's ``need_check_feed`` declarations.  Errors
        name the request id — they never surface as a late jit error."""
        rows = None
        for name, value in feed.items():
            arr = np.asarray(value)
            if arr is not value:
                feed[name] = arr
            if arr.ndim < 1:
                raise ValueError(
                    "request %r: feed %r is 0-d — continuous batching "
                    "requires every feed to carry the batch dim first"
                    % (rid, name))
            if rows is None:
                rows = int(arr.shape[0])
            elif int(arr.shape[0]) != rows:
                raise ValueError(
                    "request %r: inconsistent batch dims (%r has %d "
                    "rows, expected %d)" % (rid, name, arr.shape[0],
                                            rows))
        if not feed:
            raise ValueError("request %r: empty feed" % (rid,))
        if rows > self.buckets.max_rows:
            raise ValueError(
                "request %r: %d rows exceeds the largest bucket (%d) — "
                "split the request or widen the bucket set"
                % (rid, rows, self.buckets.max_rows))
        program = getattr(tenant.predictor, "program", None)
        if program is not None:
            try:
                _check_feed_shapes(program, feed)
            except ValueError as exc:
                raise ValueError("request %r: %s" % (rid, exc)) from None
        sig = tuple(sorted((n, tuple(v.shape[1:]), str(v.dtype))
                           for n, v in feed.items()))
        return rows, sig

    def submit(self, tenant, inputs, request_id=None, sla_ms=None):
        """Enqueue one request; returns the :class:`Request` future.

        Raises :class:`QueueFullError` when the bounded queue is full
        (backpressure — the caller decides whether to retry or fail the
        client), ``ValueError`` on a malformed feed (attributed to
        ``request_id``), :class:`ServerClosedError` after ``close``.
        """
        engine = self._engines.get(tenant)
        if engine is not None:
            # decode tenant: the engine's slot scheduler owns queueing,
            # admission, and completion — returns a DecodeRequest future
            with self._cond:
                if self._closed:
                    raise ServerClosedError("server is closed")
            return engine.submit(inputs, request_id=request_id)
        t = self._tenants.get(tenant)
        if t is None:
            raise KeyError("unknown tenant %r (have %s)"
                           % (tenant, list(self._tenants)
                              + list(self._engines)))
        seq = next(self._seq)
        rid = request_id if request_id is not None else seq
        feed = self._as_feed(t, inputs)
        rows, sig = self._validate(rid, t, feed)
        if sla_ms is None:
            sla_ms = self._sla_ms
        deadline = (time.time() + sla_ms / 1000.0
                    if sla_ms is not None else None)
        req = Request(rid, tenant, feed, rows, deadline, sig, seq)
        # root of the request's trace (enqueue→respond); joins the
        # caller's active trace when there is one
        req.span = _tr.start_span("serving.request", tenant=tenant,
                                  request_id=rid, rows=rows)
        req._qspan = _tr.start_span("serving.queue_wait",
                                    parent=req.span)
        with self._cond:
            if self._crashed is not None:
                req._qspan.end("crash:DispatcherCrashedError")
                req.span.end("crash:DispatcherCrashedError")
                raise DispatcherCrashedError(
                    "server is dead: dispatcher crashed (%s: %s)"
                    % (type(self._crashed).__name__, self._crashed))
            if self._closed:
                req._qspan.end("reject:ServerClosedError")
                req.span.end("reject:ServerClosedError")
                raise ServerClosedError("server is closed")
            depth = sum(len(x.queue) for x in self._tenants.values())
            if depth >= self._queue_cap:
                self._count("rejected")
                _obs.record_serving_reject()
                req._qspan.end("reject:QueueFullError")
                req.span.end("reject:QueueFullError")
                raise QueueFullError(
                    "queue full (%d queued, cap %d) — backpressure"
                    % (depth, self._queue_cap))
            t.queue.append(req)
            self._count("submitted")
            self._cond.notify()
        _obs.record_serving_request(tenant)
        _obs.set_serving_depths(depth + 1, len(self._inflight))
        return req

    # ------------------------------------------------------------------
    # dispatcher
    # ------------------------------------------------------------------

    def start(self):
        with self._cond:
            if self._crashed is not None:
                raise DispatcherCrashedError(
                    "server is dead: dispatcher crashed (%s: %s)"
                    % (type(self._crashed).__name__, self._crashed))
            if self._closed:
                raise ServerClosedError("server is closed")
            if self._running:
                return self
            self._running = True
        for engine in self._engines.values():
            engine.start()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="paddle_tpu-serving")
        self._thread.start()
        return self

    def close(self, timeout=60.0):
        """Stop accepting work, drain queued + in-flight requests, join
        the dispatcher."""
        with self._cond:
            self._closed = True
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        for engine in self._engines.values():
            engine.close(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _has_queued_locked(self):
        return any(t.queue for t in self._tenants.values())

    def _loop(self):
        try:
            self._dispatch_loop()
        except Exception as exc:  # noqa: BLE001 — last-resort net: the
            # per-batch guards in _dispatch_loop already contain
            # request-attributable failures; anything landing here is a
            # dispatcher bug and must not strand blocked clients
            self._dispatcher_crashed(exc)

    def _dispatcher_crashed(self, exc):
        with self._cond:
            self._crashed = exc
            self._closed = True
            self._running = False
            pending = []
            for t in self._tenants.values():
                pending.extend(t.queue)
                t.queue = []
            self._cond.notify_all()
        for entry in self._inflight:
            pending.extend(entry.requests)
            entry.span.end("crash:DispatcherCrashedError")
        self._inflight = []
        err = DispatcherCrashedError(
            "serving dispatcher thread crashed: %s: %s"
            % (type(exc).__name__, exc))
        err.__cause__ = exc
        to_fail = [r for r in pending if not r.done()]
        # journal + count BEFORE unblocking clients: whoever observes
        # the typed error can rely on the incident being on disk
        self._count("failed", len(to_fail))
        _obs.record_dispatcher_died(
            "%s: %s" % (type(exc).__name__, exc), len(to_fail),
            trace=next((r.span.trace_id for r in pending
                        if r.span.recording), None))
        for r in to_fail:
            r._fail(err)

    def _dispatch_loop(self):
        while True:
            picked = None
            with self._cond:
                while (self._running and not self._has_queued_locked()
                       and not self._inflight):
                    self._cond.wait(0.05)
                if (not self._running and not self._has_queued_locked()
                        and not self._inflight):
                    break
                if self._has_queued_locked():
                    picked = self._pick_batch_locked()
            if picked is None:
                if self._inflight:
                    self._complete_oldest()
                continue
            tenant, reqs = picked
            try:
                self._dispatch(tenant, reqs)
            except Exception as exc:  # noqa: BLE001 — fail the batch,
                for r in reqs:        # keep serving other requests
                    r._fail(exc)
                self._count("failed", len(reqs))
                continue
            while len(self._inflight) >= self._max_in_flight:
                self._complete_oldest()
        while self._inflight:
            self._complete_oldest()

    def _pick_batch_locked(self):
        """Round-robin over tenants with queued work; within the chosen
        tenant, shed unmeetable deadlines, order by (deadline, arrival)
        and coalesce same-signature requests up to the largest bucket."""
        n = len(self._order)
        for i in range(n):
            name = self._order[(self._rr + i) % n]
            t = self._tenants[name]
            if not t.queue:
                continue
            self._rr = (self._rr + i + 1) % n
            now = time.time()
            est_s = (t.est_ms / 1000.0) if t.est_ms else 0.0
            keep, shed = [], []
            for r in t.queue:
                if r.deadline is not None and now + est_s > r.deadline:
                    shed.append(r)
                else:
                    keep.append(r)
            for r in shed:
                r._fail(DeadlineExceededError(
                    "request %r shed: deadline cannot be met "
                    "(est batch service %.1fms)" % (r.id, t.est_ms or 0)))
                self._count("shed")
                _obs.record_serving_shed(name)
            keep.sort(key=lambda r: (
                r.deadline if r.deadline is not None else float("inf"),
                r.seq))
            if not keep:
                t.queue = []
                continue
            sig = keep[0].sig
            batch, rows, rest = [], 0, []
            for r in keep:
                if (r.sig == sig
                        and rows + r.rows <= self.buckets.max_rows):
                    batch.append(r)
                    rows += r.rows
                else:
                    rest.append(r)
            t.queue = rest
            formed = time.time()
            for r in batch:
                r.queue_wait_ms = (formed - r.enqueue_ts) * 1000.0
                r._qspan.end("ok")
                _obs.record_serving_queue_wait(name, r.queue_wait_ms)
            return t, batch
        return None

    def _dispatch(self, tenant, reqs):
        rows = sum(r.rows for r in reqs)
        bucket = self.buckets.bucket_for(rows)
        # the batch span parents to the first request's span and names
        # its coalesced siblings, so a trace walks request→batch even
        # when N requests share one device launch
        bspan = _tr.start_span(
            "serving.batch", parent=reqs[0].span, tenant=tenant.name,
            bucket=bucket, rows=rows, requests=len(reqs),
            coalesced=[r.span.span_id for r in reqs[1:]
                       if r.span.recording])
        try:
            with _tr.use_context(bspan.context):
                with _tr.span("serving.pad", bucket=bucket):
                    feed = {}
                    for name in reqs[0].feed:
                        feed[name] = (reqs[0].feed[name]
                                      if len(reqs) == 1
                                      else np.concatenate(
                                          [r.feed[name] for r in reqs],
                                          axis=0))
                    feed = self.buckets.pad_feed(feed, rows, bucket)
                offsets, off = [], 0
                for r in reqs:
                    offsets.append((off, off + r.rows))
                    off += r.rows
                now = time.time()
                if self._first_dispatch_ts is None:
                    self._first_dispatch_ts = now
                with _tr.span("serving.dispatch"):
                    handles = tenant.predictor.run_async(feed)
        except Exception as exc:  # noqa: BLE001 — terminal status, then
            bspan.end("error:%s" % type(exc).__name__)  # re-raised for
            raise                                 # the per-batch guard
        self._inflight.append(_InFlight(tenant, reqs, offsets, bucket,
                                        handles, now, span=bspan))
        if len(self.dispatch_log) < 4096:
            self.dispatch_log.append((tenant.name, bucket, rows))
        _obs.record_serving_batch(tenant.name, bucket, rows)
        with self._cond:
            depth = sum(len(x.queue) for x in self._tenants.values())
        _obs.set_serving_depths(depth, len(self._inflight))

    def _complete_oldest(self):
        from .. import pipeline as pl

        entry = self._inflight.pop(0)
        sync_t0 = time.time()
        # the window dispatch→sync-start is device compute overlapped
        # with anything the dispatcher did meanwhile — attributed as a
        # retroactive child span of the batch
        _tr.start_span("serving.device", parent=entry.span,
                       start_ts=entry.dispatch_ts,
                       bucket=entry.bucket).end(
            dur_ms=(sync_t0 - entry.dispatch_ts) * 1000.0)
        sspan = _tr.start_span("serving.sync", parent=entry.span,
                               handles=len(entry.handles)
                               if hasattr(entry.handles, "__len__")
                               else 1)
        try:
            outputs = pl.materialize(entry.handles)
        except Exception as exc:  # noqa: BLE001
            sspan.end("error:%s" % type(exc).__name__)
            entry.span.end("error:%s" % type(exc).__name__)
            for r in entry.requests:
                r._fail(exc)
            self._count("failed", len(entry.requests))
            return
        sspan.end("ok")
        now = time.time()
        sync_ms = (now - sync_t0) * 1000.0
        _obs.record_serving_sync(entry.tenant.name, sync_ms)
        service_ms = (now - entry.dispatch_ts) * 1000.0
        t = entry.tenant
        t.est_ms = (service_ms if t.est_ms is None
                    else (1 - self.EST_ALPHA) * t.est_ms
                    + self.EST_ALPHA * service_ms)
        for r, (a, b) in zip(entry.requests, entry.offsets):
            r._complete(self.buckets.slice_rows(outputs, a, b,
                                                entry.bucket))
            _obs.record_serving_done(t.name, r.latency_ms)
        entry.span.set_attr("service_ms", round(service_ms, 3))
        entry.span.end("ok")
        self._count("completed", len(entry.requests))
        self._last_complete_ts = now
        qps = self._qps_locked()
        if qps is not None:
            _obs.set_serving_throughput(qps)

    def _count(self, key, n=1):
        with self.stats_lock:
            self._counts[key] += n

    def _qps_locked(self):
        if (self._first_dispatch_ts is None
                or self._last_complete_ts is None):
            return None
        span = self._last_complete_ts - self._first_dispatch_ts
        if span <= 0:
            return None
        with self.stats_lock:
            done = self._counts["completed"]
        return done / span

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def warmup(self, sample_feeds):
        """Pre-compile every bucket signature: ``sample_feeds`` maps
        tenant name → a 1-row feed; each bucket size is run once
        synchronously, so the serving loop never pays a compile and
        the jit cache is exactly one entry per bucket."""
        for name, feed in sample_feeds.items():
            t = self._tenants[name]
            feed = self._as_feed(t, feed)
            feed = {n: np.asarray(v) for n, v in feed.items()}
            for size in self.buckets.sizes:
                padded = self.buckets.pad_feed(feed, 1, size)
                pl_handles = t.predictor.run_async(padded)
                from .. import pipeline as pl

                pl.materialize(pl_handles)
        return self

    def stats(self):
        with self.stats_lock:
            counts = dict(self._counts)
        with self._cond:
            depth = sum(len(t.queue) for t in self._tenants.values())
        counts.update(
            queue_depth=depth,
            inflight=len(self._inflight),
            tenants=list(self._tenants),
            buckets=list(self.buckets.sizes),
            dispatches=len(self.dispatch_log),
            est_ms={n: t.est_ms for n, t in self._tenants.items()},
            qps=self._qps_locked(),
            shed_rate=(counts["shed"] / counts["submitted"]
                       if counts["submitted"] else 0.0),
            zero_sync={n: c.ok for n, c in self.certificates.items()},
        )
        if self._engines:
            counts["decode"] = {n: e.stats()
                                for n, e in self._engines.items()}
        return counts
