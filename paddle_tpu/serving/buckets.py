"""Shape buckets: the serving-side jit-cache discipline.

The Executor jit cache is keyed (among other things) by the feed shape
signature, so every distinct request batch size is a fresh XLA compile.
Serving traffic therefore runs through a SMALL FIXED SET of padded batch
sizes: a request batch of ``n`` rows is padded up to the smallest bucket
``>= n`` (by repeating its last row — always a valid row, so int id
feeds stay in-vocab) and the real rows are sliced back out of the fetch
results.  Bucket count is capped, which bounds compile count and
steady-state latency (cf. Operator Fusion in XLA, arXiv 2301.13062:
compiled-artifact reuse dominates end-to-end cost).

The bucket set comes from, in priority order: an explicit argument, the
``PADDLE_TPU_SERVING_BUCKETS`` env override (``"1,2,4,8"``), or a
derivation from observed traffic (:func:`derive_buckets`).
"""

import os

import numpy as np

__all__ = [
    "BUCKETS_ENV",
    "BUCKET_CAP_ENV",
    "SEQ_BUCKETS_ENV",
    "DEFAULT_BUCKETS",
    "ShapeBuckets",
    "bucket_cap",
    "derive_buckets",
    "parse_buckets",
    "resolve_buckets",
]

BUCKETS_ENV = "PADDLE_TPU_SERVING_BUCKETS"
BUCKET_CAP_ENV = "PADDLE_TPU_SERVING_BUCKET_CAP"
# optional second bucket axis: padded sequence (prompt) lengths for
# decode tenants — each (batch, seq) pair is one jit signature
SEQ_BUCKETS_ENV = "PADDLE_TPU_SERVING_SEQ_BUCKETS"
DEFAULT_BUCKETS = (1, 2, 4, 8)


def bucket_cap(default=8):
    """Maximum number of buckets (== maximum jit signatures per feed
    shape family).  Env-overridable via ``PADDLE_TPU_SERVING_BUCKET_CAP``."""
    try:
        cap = int(os.environ.get(BUCKET_CAP_ENV, default))
    except ValueError:
        cap = default
    return max(1, cap)


def parse_buckets(spec):
    """``"1,2,4,8"`` (or an iterable of ints) → sorted unique tuple."""
    if isinstance(spec, str):
        parts = [p for p in spec.replace(";", ",").split(",") if p.strip()]
        sizes = [int(p) for p in parts]
    else:
        sizes = [int(s) for s in spec]
    if not sizes or any(s < 1 for s in sizes):
        raise ValueError("bucket sizes must be positive ints, got %r"
                         % (spec,))
    return tuple(sorted(set(sizes)))


def _pow2_at_least(n):
    p = 1
    while p < n:
        p *= 2
    return p


def derive_buckets(observed_sizes, cap=None, max_batch=None):
    """Derive a bucket set from observed request batch sizes.

    Each observed size is rounded up to the next power of two (padding
    waste < 2x worst case), then the unique sizes are thinned to ``cap``
    by keeping the smallest and largest and a geometric subsample in
    between — the ends bound waste for the extreme sizes, the interior
    keeps the padding ratio roughly uniform.
    """
    cap = bucket_cap() if cap is None else max(1, int(cap))
    sizes = sorted({_pow2_at_least(int(s)) for s in observed_sizes
                    if int(s) >= 1})
    if max_batch is not None:
        sizes = [s for s in sizes if s <= max_batch] or \
            [_pow2_at_least(int(max_batch))]
    if not sizes:
        return DEFAULT_BUCKETS[:cap]
    if len(sizes) <= cap:
        return tuple(sizes)
    # geometric subsample keeping both ends
    idx = np.unique(np.round(
        np.linspace(0, len(sizes) - 1, cap)).astype(int))
    return tuple(sizes[i] for i in idx)


def resolve_buckets(explicit=None, observed=None, cap=None, seq=None,
                    seq_observed=None):
    """Bucket-set precedence: explicit arg > env override > derived from
    observed traffic > :data:`DEFAULT_BUCKETS`.  Always returns a sorted
    tuple of at most ``cap`` sizes (explicit/env sets larger than the
    cap are rejected — a silent truncation would change which shapes
    compile).

    With a sequence-length axis requested — ``seq`` (explicit sizes),
    the ``PADDLE_TPU_SERVING_SEQ_BUCKETS`` env, or ``seq_observed``
    (observed prompt lengths) — the return value is the PAIR
    ``(batch_sizes, seq_sizes)``: a decode tenant's jit signatures
    cover (batch, prompt-length), one compile per pair.  With no seq
    signal at all the single-axis return is unchanged — existing
    callers never see the pair."""
    cap = bucket_cap() if cap is None else max(1, int(cap))
    if explicit is not None:
        sizes = parse_buckets(explicit)
    else:
        env = os.environ.get(BUCKETS_ENV)
        if env:
            sizes = parse_buckets(env)
        elif observed:
            sizes = derive_buckets(observed, cap=cap)
        else:
            sizes = DEFAULT_BUCKETS
    if len(sizes) > cap:
        raise ValueError(
            "bucket set %r exceeds the cap of %d buckets (raise %s or "
            "thin the set — every bucket is one jit signature)"
            % (sizes, cap, BUCKET_CAP_ENV))
    seq_env = os.environ.get(SEQ_BUCKETS_ENV)
    if seq is None and not seq_env and not seq_observed:
        return sizes
    if seq is not None:
        seq_sizes = parse_buckets(seq)
    elif seq_env:
        seq_sizes = parse_buckets(seq_env)
    else:
        seq_sizes = derive_buckets(seq_observed, cap=cap)
    if len(sizes) * len(seq_sizes) > cap * cap:
        raise ValueError(
            "bucket grid %r x %r exceeds %d signatures (every "
            "(batch, seq) pair is one jit compile)"
            % (sizes, seq_sizes, cap * cap))
    return sizes, seq_sizes


class ShapeBuckets:
    """The fixed bucket set plus the pad/slice mechanics.

    ``seq_sizes`` adds the optional second axis (padded prompt lengths
    for decode tenants); it stays None — and every existing behavior is
    untouched — unless a seq signal is given."""

    def __init__(self, sizes=None, observed=None, cap=None,
                 seq_sizes=None, seq_observed=None):
        resolved = resolve_buckets(explicit=sizes, observed=observed,
                                   cap=cap, seq=seq_sizes,
                                   seq_observed=seq_observed)
        if isinstance(resolved[0], tuple):
            self.sizes, self.seq_sizes = resolved
        else:
            self.sizes, self.seq_sizes = resolved, None

    @property
    def max_rows(self):
        return self.sizes[-1]

    def bucket_for(self, rows):
        """Smallest bucket that fits ``rows``; None when ``rows`` exceeds
        the largest bucket (the caller splits the batch)."""
        for s in self.sizes:
            if s >= rows:
                return s
        return None

    def bucket_for_seq(self, length):
        """Smallest sequence-length bucket that fits ``length``; None
        when it exceeds the largest (the caller truncates or rejects).
        Raises if no seq axis was configured."""
        if self.seq_sizes is None:
            raise ValueError(
                "no sequence-length axis configured (pass seq_sizes/"
                "seq_observed or set %s)" % SEQ_BUCKETS_ENV)
        for s in self.seq_sizes:
            if s >= length:
                return s
        return None

    @staticmethod
    def pad_seq(array, length, bucket, axis=1, value=0):
        """Pad ``array`` (dim ``axis`` == ``length``) up to ``bucket``
        along the sequence axis with ``value`` (decode programs mask by
        prompt_len, so the pad content never matters); no-op when
        already full."""
        if length == bucket:
            return array
        widths = [(0, 0)] * array.ndim
        widths[axis] = (0, bucket - length)
        return np.pad(array, widths, constant_values=value)

    @staticmethod
    def pad_rows(array, rows, bucket):
        """Pad ``array`` (leading dim == ``rows``) up to ``bucket`` rows
        by repeating the last real row; no-op when already full."""
        if rows == bucket:
            return array
        pad = np.repeat(array[rows - 1:rows], bucket - rows, axis=0)
        return np.concatenate([array[:rows], pad], axis=0)

    def pad_feed(self, feed, rows, bucket):
        """Pad every batch-leading array in a name→array feed dict."""
        return {n: self.pad_rows(v, rows, bucket)
                if getattr(v, "ndim", 0) >= 1 and v.shape[0] == rows
                else v
                for n, v in feed.items()}

    @staticmethod
    def slice_rows(outputs, start, stop, bucket):
        """Extract one request's rows from padded fetch results.  Outputs
        whose leading dim is not the bucket size (a scalar score, a
        reduced stat) are returned whole to every request."""
        out = []
        for o in outputs:
            if getattr(o, "ndim", 0) >= 1 and o.shape[0] == bucket:
                out.append(o[start:stop])
            else:
                out.append(o)
        return out

    def __repr__(self):
        return "ShapeBuckets(%s)" % (list(self.sizes),)
