"""Continuous-batching multi-tenant predictor serving.

The layer that turns the async predictor substrate into sustained
traffic: a bounded request queue, shape-bucket padding (jit-cache
bounded), an in-flight dispatcher over the zero-sync certified hot
loop, SLA shedding, per-tenant fairness, and a load generator.  CLI:
``python -m paddle_tpu.tools.serve``.
"""

from .buckets import (BUCKET_CAP_ENV, BUCKETS_ENV, DEFAULT_BUCKETS,
                      SEQ_BUCKETS_ENV, ShapeBuckets, bucket_cap,
                      derive_buckets, parse_buckets, resolve_buckets)
from .decode import DecodeEngine, DecodeRequest, GenerationConfig
from .loadgen import make_feed_sampler, percentile, run_load
from .paging import (PAGED_KV_ENV, BlockAllocator, KVPoolExhausted,
                     blocks_needed, build_block_table,
                     paged_kv_enabled)
from .server import (DeadlineExceededError, DispatcherCrashedError,
                     PredictorServer, QueueFullError, Request,
                     ServerClosedError, ServingError)
from .speculative import SpeculativeDecoder, ngram_draft

__all__ = [
    "BUCKETS_ENV",
    "BUCKET_CAP_ENV",
    "BlockAllocator",
    "DEFAULT_BUCKETS",
    "DeadlineExceededError",
    "DecodeEngine",
    "DecodeRequest",
    "DispatcherCrashedError",
    "GenerationConfig",
    "KVPoolExhausted",
    "PAGED_KV_ENV",
    "PredictorServer",
    "QueueFullError",
    "Request",
    "SEQ_BUCKETS_ENV",
    "ServerClosedError",
    "ServingError",
    "ShapeBuckets",
    "SpeculativeDecoder",
    "blocks_needed",
    "bucket_cap",
    "build_block_table",
    "derive_buckets",
    "make_feed_sampler",
    "ngram_draft",
    "paged_kv_enabled",
    "parse_buckets",
    "percentile",
    "resolve_buckets",
    "run_load",
]
