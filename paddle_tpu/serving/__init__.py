"""Continuous-batching multi-tenant predictor serving.

The layer that turns the async predictor substrate into sustained
traffic: a bounded request queue, shape-bucket padding (jit-cache
bounded), an in-flight dispatcher over the zero-sync certified hot
loop, SLA shedding, per-tenant fairness, and a load generator.  CLI:
``python -m paddle_tpu.tools.serve``.
"""

from .buckets import (BUCKET_CAP_ENV, BUCKETS_ENV, DEFAULT_BUCKETS,
                      SEQ_BUCKETS_ENV, ShapeBuckets, bucket_cap,
                      derive_buckets, parse_buckets, resolve_buckets)
from .decode import DecodeEngine, DecodeRequest, GenerationConfig
from .loadgen import make_feed_sampler, percentile, run_load
from .server import (DeadlineExceededError, DispatcherCrashedError,
                     PredictorServer, QueueFullError, Request,
                     ServerClosedError, ServingError)

__all__ = [
    "BUCKETS_ENV",
    "BUCKET_CAP_ENV",
    "DEFAULT_BUCKETS",
    "SEQ_BUCKETS_ENV",
    "DeadlineExceededError",
    "DecodeEngine",
    "DecodeRequest",
    "DispatcherCrashedError",
    "GenerationConfig",
    "PredictorServer",
    "QueueFullError",
    "Request",
    "ServerClosedError",
    "ServingError",
    "ShapeBuckets",
    "bucket_cap",
    "derive_buckets",
    "make_feed_sampler",
    "parse_buckets",
    "percentile",
    "resolve_buckets",
    "run_load",
]
