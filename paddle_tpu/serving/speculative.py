"""Speculative decoding over the paged serving tier (ISSUE 19).

The target model's paged **step program already is a verifier**: it
takes ``k+1`` independent rows, writes every row's K/V through the
block table BEFORE the attention reads, and masks row ``i`` to its own
cursor — so feeding ``[last_emitted, d_1 .. d_k]`` with cursors
``c, c+1 .. c+k`` and the SAME block table on every row scores the
whole draft window in ONE launch: row ``i``'s greedy output ``g_i`` is
exactly the token the target would have produced after
``prefix + d_1 .. d_{i-1}``.  No new program family, no second cache.

Acceptance is exact prefix-match greedy: keep ``d_i`` while
``d_i == g_i``, then emit the target's own correction ``g_{a+1}`` as
the bonus token — so the emitted stream is BIT-IDENTICAL to plain
greedy decoding regardless of the draft's quality; the draft only
moves the speed.  A rejected tail needs no cache surgery: the cursor
resets and the stale positions are masked until the next round
overwrites them in place.

Drafts:

* ``draft="ngram"`` — prompt-lookup (host-side, zero device cost):
  propose the continuation of the most recent earlier occurrence of
  the current last token.  Free tokens on repetitive text.
* ``draft=<paged model>`` — a cheap **draft-model tenant** with its
  own engine, scope and KV pool: confirmed tokens are streamed into
  its cache, proposals come from running its own greedy chain ``k``
  steps ahead (speculative draft-side writes roll back by cursor
  reset, same trick as the target).
* ``draft=<callable>`` — ``f(context_tokens, k) -> [k] ints`` (test
  hook).

Telemetry: ``spec_tokens_proposed/accepted_total`` counters and the
``spec_acceptance_rate`` gauge (``record_spec_round``) that
``bench --child decode`` gates on.
"""

import numpy as np

from ..observability import runtime as _obs
from .decode import DecodeEngine, GenerationConfig
from .paging import blocks_needed, build_block_table

__all__ = ["SpeculativeDecoder", "ngram_draft"]


def ngram_draft(context, k):
    """Prompt-lookup draft: continuation of the most recent earlier
    occurrence of the last token; padded by repeating the tail."""
    context = [int(t) for t in context]
    last = context[-1] if context else 0
    prop = []
    for i in range(len(context) - 2, -1, -1):
        if context[i] == last:
            prop = context[i + 1:i + 1 + k]
            break
    while len(prop) < k:
        prop.append(prop[-1] if prop else last)
    return prop[:k]


class _ModelDraft:
    """The draft-model tenant: slots=1 paged engine driven manually.

    ``propose(confirmed)`` first streams the not-yet-ingested
    confirmed tokens through the draft's step program (each run writes
    that token's K/V and returns the draft's greedy next token), then
    rolls its own chain ``k`` ahead; the chain's writes are
    speculative and undone by resetting the cursor — the next
    confirmed ingestion overwrites the same positions."""

    def __init__(self, engine, k):
        self.eng = engine
        self.k = k
        self.blocks = None
        self.table = None
        self.cursor = 0
        self.ingested = 0
        self.pred = None
        self._steps = 0

    def start(self, prompt, max_new):
        eng = self.eng
        n = int(prompt.size)
        rows = min(n + max_new + self.k + 1, eng.max_len)
        self.blocks = eng._pool.allocate(
            blocks_needed(rows, eng.block_len))
        self.table = build_block_table(self.blocks, eng.max_blocks)
        L = eng.buckets.bucket_for_seq(n)
        if L is None:
            raise ValueError(
                "prompt of %d tokens exceeds the draft model's largest "
                "prompt bucket (%d)" % (n, eng.buckets.seq_sizes[-1]))
        padded = np.zeros((1, L), dtype="int32")
        padded[0, :n] = prompt
        main, fetch = eng._prefill[L]
        out = eng._exe.run(
            main,
            feed={"prompt_ids": padded,
                  "prompt_len": np.asarray([n], "int32"),
                  "block_table": self.table.reshape(1, -1)},
            fetch_list=[fetch], scope=eng.scope)
        self.pred = int(np.asarray(out[0]).reshape(-1)[0])
        self.cursor = n
        self.ingested = 0
        self._prompt_len = n

    def _step(self, token):
        eng = self.eng
        self._steps += 1
        out = eng._exe.run(
            eng._step_prog,
            feed={"cur_ids": np.asarray([token], "int32"),
                  "cursors": np.asarray([self.cursor], "int32"),
                  "block_tables": self.table.reshape(1, -1),
                  "step": np.asarray([self._steps], "int32")},
            fetch_list=[eng._step_fetch], scope=eng.scope)
        self.cursor += 1
        return int(np.asarray(out[0]).reshape(-1)[0])

    def propose(self, context, k):
        confirmed = context[self._prompt_len:]
        for t in confirmed[self.ingested:]:
            if self.cursor >= self.eng.max_len - 1:
                break
            self.pred = self._step(int(t))
            self.ingested += 1
        drafts, cur = [], self.pred
        save = self.cursor
        for i in range(k):
            drafts.append(cur)
            if i + 1 < k and self.cursor < self.eng.max_len - 1:
                cur = self._step(cur)
        self.cursor = save  # roll back the speculative chain
        return drafts

    def finish(self):
        if self.blocks:
            self.eng._pool.free(self.blocks)
            self.blocks = None


class SpeculativeDecoder:
    """Single-stream speculative greedy generation over a paged model.

    Wraps a :class:`DecodeEngine` built with ``slots = k+1`` (never
    started — the decoder drives the programs directly): the engine's
    paged step program doubles as the multi-query-row verifier.
    ``generate`` returns ``(tokens, info)`` with the emitted stream
    bit-identical to plain greedy decoding of the same model."""

    def __init__(self, model, draft="ngram", k=4, prompt_buckets=(32,),
                 config=None, place=None, name="spec", block_len=None,
                 num_blocks=None):
        self.k = int(k)
        if self.k < 1:
            raise ValueError("k must be >= 1, got %d" % k)
        self.name = name
        self.config = config or GenerationConfig()
        if self.config.strategy != "greedy":
            raise ValueError(
                "speculative decoding is exact for greedy sampling "
                "only; got strategy=%r" % (self.config.strategy,))
        self._eng = DecodeEngine(
            model, slots=self.k + 1, prompt_buckets=prompt_buckets,
            config=self.config, place=place, name=name,
            auto_start=False, paged=True, block_len=block_len,
            num_blocks=num_blocks)
        self._draft_fn = None
        self._draft = None
        if callable(draft):
            self._draft_fn = draft
        elif draft == "ngram":
            self._draft_fn = ngram_draft
        else:
            deng = DecodeEngine(
                draft, slots=1, prompt_buckets=prompt_buckets,
                config=self.config, place=place,
                name="%s.draft" % name, auto_start=False, paged=True)
            self._draft = _ModelDraft(deng, self.k)

    @property
    def engine(self):
        return self._eng

    def coresident_programs(self):
        """Target + draft-tenant program families for the co-residency
        proof (the draft engine has its own scope and cache names, so
        the proof shows NO overlap — they could share a chip)."""
        progs = list(self._eng.coresident_programs())
        if self._draft is not None:
            progs.extend(self._draft.eng.coresident_programs())
        return progs

    def close(self):
        self._eng.close()
        if self._draft is not None:
            self._draft.eng.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def generate(self, prompt, max_new_tokens=None):
        eng = self._eng
        k = self.k
        prompt = np.asarray(prompt, dtype="int32").reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.config.max_new_tokens)
        L = eng.buckets.bucket_for_seq(prompt.size)
        if L is None:
            raise ValueError(
                "prompt of %d tokens exceeds the largest prompt "
                "bucket (%d)" % (prompt.size, eng.buckets.seq_sizes[-1]))
        rows = int(prompt.size) + max_new + k
        if rows > eng.max_len:
            raise ValueError(
                "prompt (%d) + generation budget (%d) + draft window "
                "(%d) exceeds the cache depth %d — shrink k or "
                "max_new_tokens" % (prompt.size, max_new, k,
                                    eng.max_len))
        blocks = eng._pool.allocate(blocks_needed(rows, eng.block_len))
        if self._draft is not None:
            self._draft.start(prompt, max_new)
        try:
            return self._generate(prompt, max_new, L, blocks)
        finally:
            eng._pool.free(blocks)
            if self._draft is not None:
                self._draft.finish()

    def _propose(self, context):
        if self._draft is not None:
            return self._draft.propose(context, self.k)
        return list(self._draft_fn(context, self.k))[:self.k]

    def _generate(self, prompt, max_new, L, blocks):
        eng, k = self._eng, self.k
        table = build_block_table(blocks, eng.max_blocks)
        padded = np.zeros((1, L), dtype="int32")
        padded[0, :prompt.size] = prompt
        main, fetch = eng._prefill[L]
        out = eng._exe.run(
            main,
            feed={"prompt_ids": padded,
                  "prompt_len": np.asarray([prompt.size], "int32"),
                  "block_table": table.reshape(1, -1)},
            fetch_list=[fetch], scope=eng.scope)
        first = int(np.asarray(out[0]).reshape(-1)[0])
        tokens = [first]
        cursor = int(prompt.size)
        context = [int(t) for t in prompt]
        eos = self.config.eos_id
        done = eos is not None and first == eos
        tables = np.repeat(table.reshape(1, -1), k + 1, axis=0)
        rounds = proposed = accepted = 0
        while not done and len(tokens) < max_new:
            drafts = self._propose(context + tokens)
            if len(drafts) != k:
                raise ValueError("draft proposed %d tokens, expected "
                                 "%d" % (len(drafts), k))
            cur = np.empty((k + 1,), dtype="int32")
            cur[0] = tokens[-1]
            cur[1:] = drafts
            cursors = (cursor
                       + np.arange(k + 1, dtype="int32"))
            rounds += 1
            out = eng._exe.run(
                eng._step_prog,
                feed={"cur_ids": cur, "cursors": cursors,
                      "block_tables": tables,
                      "step": np.asarray([rounds], "int32")},
                fetch_list=[eng._step_fetch], scope=eng.scope)
            g = np.asarray(out[0]).reshape(-1)
            a = 0
            while a < k and int(drafts[a]) == int(g[a]):
                a += 1
            proposed += k
            accepted += a
            _obs.record_spec_round(self.name, k, a)
            for i in range(a + 1):
                tokens.append(int(g[i]))
                cursor += 1
                if eos is not None and tokens[-1] == eos:
                    done = True
                    break
                if len(tokens) >= max_new:
                    break
        info = {"generated_len": len(tokens), "rounds": rounds,
                "proposed": proposed, "accepted": accepted,
                "acceptance_rate":
                    accepted / float(proposed) if proposed else 0.0}
        return tokens, info
