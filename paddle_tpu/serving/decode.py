"""Continuous-batching autoregressive decode engine (the serving side
of the ISSUE-14 tentpole).

One resident KV cache per layer, shape ``[slots, H, Tmax, Dh]`` with a
per-slot integer cursor (``per_row=True`` writes/reads), is carved into
``slots`` independent cache blocks.  Requests flow through two program
families that share the caches by (persistable) var name in the
engine's private Scope:

* **prefill** — one program per prompt-length bucket (the
  :mod:`~paddle_tpu.serving.buckets` seq axis): feeds one request's
  padded ``[1, L]`` prompt plus its slot index, writes K/V rows
  ``[0, plen)`` into that slot's cache block and returns the first
  sampled token.  Runs whenever a slot is FREE and a request is queued
  — admission happens mid-stream, between decode steps, without
  touching the other slots' state.
* **decode step** — ONE program for all slots: feeds the current token
  and cursor per slot, ring-writes K/V at each slot's own depth,
  flash-decode-attends masked to each slot's cursor, samples the next
  token per slot.  Every step is the same feed signature, so the jit
  cache holds exactly one entry for the whole steady state regardless
  of how long any request has been generating.

The scheduler thread interleaves the two: step the active slots, drain
finished requests, admit queued requests into the freed cache blocks,
repeat.  The per-step host hop (the sampled ``[slots]`` token vector)
is the admission decision — the device work itself stays one compiled
program.  Telemetry: ``serving_decode_tokens_total``,
``serving_generated_len`` / ``serving_ttft_ms`` histograms and the
``decode_tokens_per_sec`` gauge (``tools.monitor``), plus
``serving.prefill`` / ``serving.decode`` spans so ``tools.trace
--serving`` attributes time between the two phases.

**Paged mode (ISSUE 19).**  When the model also supplies
``build_prefill_paged`` / ``build_step_paged`` (and
``PADDLE_TPU_PAGED_KV`` isn't ``0``), the resident cache becomes a
paged pool ``[num_blocks, H, block_len, Dh]`` with a free-list
(:mod:`~paddle_tpu.serving.paging`): a stream owns exactly
``ceil(rows / block_len)`` blocks named by its block table instead of a
full ``Tmax`` ring row, so the concurrent-stream count is bounded by
ACTUAL cache usage, not by ``slots × Tmax`` reservations — the ≥4x
streams-per-chip lever bench's A/B gates.  Admission allocates
all-or-nothing (a short pool queues the request, never truncates it).

**Disaggregated prefill (``disaggregate=True``, paged only).**  Prefill
(compute-bound) runs on its own worker thread with its own programs;
finished prefills hand the request to the decode scheduler as a
**KV-block handoff** — ownership of the block-table entries transfers,
the K/V rows never move.  The ``serving.kv_handoff`` span covers
prefill-done → slot activation so ``tools.trace --serving`` splits
TTFT into prefill vs handoff vs first decode step.  Both program
families declare the pool vars as ``_kv_handoff_vars`` so the PR-10
co-residency proof records the shared-write as a declared handoff
(INFO) instead of rejecting the placement; device mutation is
serialized through one executor lock (single-host co-residency — on a
real disaggregated deployment the tenants hold different chips).
"""

import threading
import time

import numpy as np

from ..observability import runtime as _obs
from ..observability import tracing as _tr
from .buckets import ShapeBuckets
from .paging import (BlockAllocator, blocks_needed, build_block_table,
                     paged_kv_enabled)

__all__ = ["DecodeEngine", "DecodeRequest", "GenerationConfig"]


class GenerationConfig:
    """Sampling knobs a decode tenant applies to every request."""

    __slots__ = ("strategy", "k", "p", "temperature", "seed",
                 "max_new_tokens", "eos_id")

    def __init__(self, strategy="greedy", k=8, p=0.9, temperature=1.0,
                 seed=0, max_new_tokens=64, eos_id=None):
        self.strategy = strategy
        self.k = int(k)
        self.p = float(p)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id


class DecodeRequest:
    """One generation request: a future resolving to
    ``(tokens, info)`` — the generated ids (eos included when hit) and
    ``{"generated_len", "ttft_ms", "latency_ms"}``."""

    __slots__ = ("id", "prompt", "enqueue_ts", "_event", "_tokens",
                 "_error", "info", "span", "first_token_ts", "tenant")

    def __init__(self, rid, prompt):
        self.id = rid
        self.prompt = prompt
        self.enqueue_ts = time.time()
        self._event = threading.Event()
        self._tokens = None
        self._error = None
        self.info = {}
        self.span = _tr.NULL_SPAN
        self.first_token_ts = None
        self.tenant = None

    @property
    def latency_ms(self):
        """Loadgen-compatible latency accessor (None until done)."""
        return self.info.get("latency_ms")

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("decode request %r not completed within "
                               "%ss" % (self.id, timeout))
        if self._error is not None:
            raise self._error
        return self._tokens, self.info

    def _complete(self, tokens):
        self._tokens = list(tokens)
        self.info["generated_len"] = len(self._tokens)
        self.info["latency_ms"] = (time.time()
                                   - self.enqueue_ts) * 1000.0
        if self.first_token_ts is not None:
            self.info["ttft_ms"] = (self.first_token_ts
                                    - self.enqueue_ts) * 1000.0
        self.span.set_attr("generated_len", len(self._tokens))
        self.span.end("ok")
        self._event.set()

    def _fail(self, exc):
        self._error = exc
        self.span.end("error:%s" % type(exc).__name__)
        self._event.set()


class _Slot:
    __slots__ = ("request", "cursor", "tokens", "finished", "blocks",
                 "table")

    def __init__(self):
        self.request = None   # None == free cache block
        self.cursor = 0
        self.tokens = []
        self.finished = False
        self.blocks = []      # paged mode: owned KV-pool block ids
        self.table = None     # paged mode: [max_blocks] int32, -1 pad


class DecodeEngine:
    """The decode tenant a :class:`PredictorServer` serves.

    ``model`` supplies the two graph builders (sharing parameters by
    ParamAttr name):

    * ``model.build_prefill(prompt, plen, slot, caches) -> logits`` —
      prompt ``[1, L]`` ids, ``plen``/``slot`` ``[1]`` int32; must write
      the prompt's K/V into cache row ``slot`` (``kv_cache_prefill``
      with ``slot=``) and return the LAST real position's logits
      ``[1, V]``.
    * ``model.build_step(cur, cursors, caches) -> logits`` — ``cur``
      ``[slots]`` ids, ``cursors`` ``[slots]`` int32 (each slot's own
      depth); per-row ring-write + flash-decode; logits ``[slots, V]``.

    plus ``model.cache_spec() -> (layers, heads, max_len, head_dim)``
    and optionally ``model.init_params(program, startup, exe, scope)``
    to load/initialize weights (called once inside the engine scope).

    A model that ALSO supplies the paged builders opts into the paged
    KV pool (unless ``PADDLE_TPU_PAGED_KV=0`` or ``paged=False``):

    * ``model.build_prefill_paged(prompt, plen, table, caches)`` —
      ``table`` ``[1, max_blocks]`` int32 (-1 padded); writes the
      prompt's K/V through the block table (``paged_kv_cache_prefill``).
    * ``model.build_step_paged(cur, cursors, tables, caches)`` —
      ``tables`` ``[slots, max_blocks]``; per-row paged write +
      ``paged_flash_decode`` masked to each row's cursor.

    The paged cache shape is ``[num_blocks, H, block_len, Dh]``;
    ``num_blocks`` defaults to ``slots * max_len / block_len`` (the
    same HBM the ring reserved) but any pool size works — admission
    backpressures on the free-list instead of on ``slots``.
    """

    def __init__(self, model, slots=2, prompt_buckets=(32,),
                 config=None, place=None, name="decode",
                 auto_start=True, paged=None, block_len=None,
                 num_blocks=None, disaggregate=False):
        import paddle_tpu as fluid
        from ..executor import Scope

        self.name = name
        self.model = model
        self.slots = int(slots)
        self.config = config or GenerationConfig()
        self.buckets = ShapeBuckets((1,), seq_sizes=prompt_buckets)
        self.scope = Scope()
        self.place = place if place is not None else fluid.TPUPlace()
        self._exe = fluid.Executor(self.place)
        self._layers, self._heads, self.max_len, self._head_dim = \
            model.cache_spec()
        self._cache_names = []
        for li in range(self._layers):
            self._cache_names.append(("%s.kcache.%d" % (name, li),
                                      "%s.vcache.%d" % (name, li)))
        model_paged = (hasattr(model, "build_prefill_paged")
                       and hasattr(model, "build_step_paged"))
        if paged is None:
            # auto: paged whenever the model can express it and the
            # PADDLE_TPU_PAGED_KV kill switch isn't 0
            paged = paged_kv_enabled() and model_paged
        self.paged = bool(paged)
        if self.paged and not model_paged:
            raise ValueError(
                "paged=True but model %r lacks build_prefill_paged/"
                "build_step_paged" % (type(model).__name__,))
        if self.paged:
            from ..ops.pallas.paged_flash_decode import paged_block_len
            bl = int(block_len) if block_len \
                else paged_block_len(self._head_dim, self.max_len)
            if self.max_len % bl != 0:
                raise ValueError(
                    "block_len %d must divide the cache depth %d (the "
                    "full-depth block table is what keeps paged greedy "
                    "bit-identical to the slot ring)"
                    % (bl, self.max_len))
            self.block_len = bl
            self.max_blocks = self.max_len // bl
            self._explicit_blocks = num_blocks is not None
            # default pool = the HBM the slot ring would have reserved
            self.num_blocks = int(num_blocks) if num_blocks \
                else self.slots * self.max_blocks
            self._pool = BlockAllocator(self.num_blocks, self.block_len)
        else:
            self.block_len = None
            self.max_blocks = 0
            self.num_blocks = 0
            self._explicit_blocks = False
            self._pool = None
        self.disaggregate = bool(disaggregate)
        if self.disaggregate and not self.paged:
            raise ValueError("disaggregate=True requires paged KV mode "
                             "(the handoff transfers block-table "
                             "entries, not cache rows)")
        self._handoff = []       # (req, blocks, table, first, ready_ts)
        self._exe_lock = threading.Lock()
        self._slots = [_Slot() for _ in range(self.slots)]
        self._queue = []
        self._cond = threading.Condition()
        self._running = False
        self._closed = False
        self._resizing = False
        self._admitting = 0
        self._step_count = 0
        self._tokens_done = 0
        self._rate_t0 = None
        self.stats_lock = threading.Lock()
        self._counts = {"submitted": 0, "completed": 0, "failed": 0,
                        "tokens": 0}
        self._build_programs()
        self._publish_pool()
        if auto_start:
            self.start()

    # ------------------------------------------------------------------
    # graph construction
    # ------------------------------------------------------------------

    def _cache_shape(self):
        if self.paged:
            return [self.num_blocks, self._heads, self.block_len,
                    self._head_dim]
        return [self.slots, self._heads, self.max_len, self._head_dim]

    @property
    def cache_bytes(self):
        """Resident KV bytes (K and V, every layer) — what the ring vs
        paged-pool HBM-equality A/B compares."""
        rows = 1
        for d in self._cache_shape():
            rows *= d
        return rows * 4 * 2 * self._layers

    def _declare_caches(self, block):
        """Declare the persistable resident caches in ``block``'s
        program — every program family names the SAME vars, so they
        alias one buffer in the engine scope."""
        caches = []
        shape = self._cache_shape()
        for kn, vn in self._cache_names:
            k = block.create_var(name=kn, shape=shape, dtype="float32",
                                 persistable=True)
            v = block.create_var(name=vn, shape=shape, dtype="float32",
                                 persistable=True)
            caches.append((k, v))
        return caches

    def _build_programs(self):
        if self.paged:
            self._build_programs_paged()
        else:
            self._build_programs_ring()
        self._exe.run(self._startup, scope=self.scope)
        self._exe.run(self._init, scope=self.scope)
        init_params = getattr(self.model, "init_params", None)
        if init_params is not None:
            init_params(self._step_prog, self._startup, self._exe,
                        self.scope)

    def _build_programs_ring(self):
        import paddle_tpu as fluid

        cfg = self.config
        fluid.unique_name.switch()

        # init: zero the caches + the model's parameter init
        init = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(init, startup):
            for k, v in self._declare_caches(init.global_block()):
                fluid.layers.fill_constant(self._cache_shape(),
                                           "float32", 0.0, out=k)
                fluid.layers.fill_constant(self._cache_shape(),
                                           "float32", 0.0, out=v)
        self._init, self._startup = init, startup

        # prefill: one program per prompt-length bucket
        self._prefill = {}
        for L in self.buckets.seq_sizes:
            main = fluid.Program()
            with fluid.program_guard(main, startup):
                prompt = fluid.layers.data(
                    "prompt_ids", shape=[1, L], dtype="int32",
                    append_batch_size=False)
                plen = fluid.layers.data(
                    "prompt_len", shape=[1], dtype="int32",
                    append_batch_size=False)
                slot = fluid.layers.data(
                    "slot", shape=[1], dtype="int32",
                    append_batch_size=False)
                caches = self._declare_caches(main.global_block())
                logits = self.model.build_prefill(prompt, plen, slot,
                                                  caches)
                first = fluid.layers.sampling(
                    logits, strategy=cfg.strategy, k=cfg.k, p=cfg.p,
                    temperature=cfg.temperature, seed=cfg.seed)
            self._prefill[L] = (main, first.name)

        # decode step: ONE program, all slots
        main = fluid.Program()
        with fluid.program_guard(main, startup):
            cur = fluid.layers.data("cur_ids", shape=[self.slots],
                                    dtype="int32",
                                    append_batch_size=False)
            cursors = fluid.layers.data("cursors", shape=[self.slots],
                                        dtype="int32",
                                        append_batch_size=False)
            step = fluid.layers.data("step", shape=[1], dtype="int32",
                                     append_batch_size=False)
            caches = self._declare_caches(main.global_block())
            logits = self.model.build_step(cur, cursors, caches)
            nxt = fluid.layers.sampling(
                logits, strategy=cfg.strategy, k=cfg.k, p=cfg.p,
                temperature=cfg.temperature, seed=cfg.seed, step=step)
        self._step_prog, self._step_fetch = main, nxt.name
        #: the program PredictorServer stamps/verifies as the hot loop
        self.program = main

    def _build_programs_paged(self):
        import paddle_tpu as fluid

        cfg = self.config
        fluid.unique_name.switch()
        mb = self.max_blocks

        init = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(init, startup):
            for k, v in self._declare_caches(init.global_block()):
                fluid.layers.fill_constant(self._cache_shape(),
                                           "float32", 0.0, out=k)
                fluid.layers.fill_constant(self._cache_shape(),
                                           "float32", 0.0, out=v)
        self._init, self._startup = init, startup

        # the pool vars prefill WRITES and decode READS+WRITES: a
        # declared KV-block handoff, not an accidental overlap — the
        # co-residency proof downgrades it to INFO only when BOTH
        # programs carry the declaration
        handoff = frozenset(n for pair in self._cache_names
                            for n in pair)

        self._prefill = {}
        for L in self.buckets.seq_sizes:
            main = fluid.Program()
            with fluid.program_guard(main, startup):
                prompt = fluid.layers.data(
                    "prompt_ids", shape=[1, L], dtype="int32",
                    append_batch_size=False)
                plen = fluid.layers.data(
                    "prompt_len", shape=[1], dtype="int32",
                    append_batch_size=False)
                table = fluid.layers.data(
                    "block_table", shape=[1, mb], dtype="int32",
                    append_batch_size=False)
                caches = self._declare_caches(main.global_block())
                logits = self.model.build_prefill_paged(
                    prompt, plen, table, caches)
                first = fluid.layers.sampling(
                    logits, strategy=cfg.strategy, k=cfg.k, p=cfg.p,
                    temperature=cfg.temperature, seed=cfg.seed)
            main._kv_handoff_vars = handoff
            self._prefill[L] = (main, first.name)

        main = fluid.Program()
        with fluid.program_guard(main, startup):
            cur = fluid.layers.data("cur_ids", shape=[self.slots],
                                    dtype="int32",
                                    append_batch_size=False)
            cursors = fluid.layers.data("cursors", shape=[self.slots],
                                        dtype="int32",
                                        append_batch_size=False)
            tables = fluid.layers.data(
                "block_tables", shape=[self.slots, mb], dtype="int32",
                append_batch_size=False)
            step = fluid.layers.data("step", shape=[1], dtype="int32",
                                     append_batch_size=False)
            caches = self._declare_caches(main.global_block())
            logits = self.model.build_step_paged(cur, cursors, tables,
                                                 caches)
            nxt = fluid.layers.sampling(
                logits, strategy=cfg.strategy, k=cfg.k, p=cfg.p,
                temperature=cfg.temperature, seed=cfg.seed, step=step)
        main._kv_handoff_vars = handoff
        self._step_prog, self._step_fetch = main, nxt.name
        #: the program PredictorServer stamps/verifies as the hot loop
        self.program = main

    # the PredictorServer tenant-introspection surface
    def get_input_names(self):
        return ["prompt_ids"]

    def get_output_names(self):
        return [self._step_fetch]

    def coresident_programs(self):
        """Every program family this engine keeps resident, as
        ``(label, program, fetch_targets)``.  With disaggregated
        prefill the prefill programs run on their own thread against
        the same scope, so the PredictorServer placement proof and
        zero-sync certification must cover them too — not just the hot
        step loop."""
        progs = [(self.name, self._step_prog, [self._step_fetch])]
        if self.disaggregate:
            for L in sorted(self._prefill):
                main, fetch = self._prefill[L]
                progs.append(("%s.prefill%d" % (self.name, L), main,
                              [fetch]))
        return progs

    # ------------------------------------------------------------------
    # client side
    # ------------------------------------------------------------------

    def submit(self, prompt, request_id=None):
        """Enqueue one prompt (1-D int array); returns the
        :class:`DecodeRequest` future."""
        prompt = np.asarray(prompt, dtype="int32").reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size > self.max_len - 1:
            raise ValueError(
                "prompt of %d tokens exceeds the cache depth %d"
                % (prompt.size, self.max_len))
        if self.buckets.bucket_for_seq(prompt.size) is None:
            raise ValueError(
                "prompt of %d tokens exceeds the largest prompt "
                "bucket (%d)" % (prompt.size,
                                 self.buckets.seq_sizes[-1]))
        if self.paged:
            need = blocks_needed(
                min(int(prompt.size) + self.config.max_new_tokens,
                    self.max_len), self.block_len)
            if need > self.num_blocks:
                raise ValueError(
                    "prompt + generation budget needs %d KV blocks but "
                    "the pool holds %d (block_len=%d) — it could never "
                    "be admitted" % (need, self.num_blocks,
                                     self.block_len))
        with self._cond:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            rid = request_id if request_id is not None \
                else len(self._queue) + self._counts["submitted"]
            req = DecodeRequest(rid, prompt)
            req.tenant = self.name
            req.span = _tr.start_span("serving.request",
                                      tenant=self.name, request_id=rid,
                                      prompt_len=int(prompt.size))
            self._queue.append(req)
            self._count("submitted")
            self._cond.notify()
        _obs.record_serving_request(self.name)
        return req

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------

    def start(self):
        with self._cond:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="paddle_tpu-decode-%s" % self.name)
        self._thread.start()
        if self.disaggregate:
            self._prefill_thread = threading.Thread(
                target=self._prefill_loop, daemon=True,
                name="paddle_tpu-prefill-%s" % self.name)
            self._prefill_thread.start()
        return self

    def close(self, timeout=60.0):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for attr in ("_thread", "_prefill_thread"):
            t = getattr(self, attr, None)
            if t is not None:
                t.join(timeout)
                setattr(self, attr, None)

    def resize(self, slots, timeout=60.0):
        """Scale the KV-cache slot count in place — the autoscaler's
        serving actuator.  Drain-to-idle semantics: admissions are held
        (queued requests stay queued), in-flight generations run to
        completion, then the cache buffers and both program families
        are rebuilt at the new count and the scheduler resumes.  No
        per-slot state needs migrating because only FREE slots exist at
        the rebuild point."""
        slots = int(slots)
        if slots < 1:
            raise ValueError("slots must be >= 1, got %d" % slots)
        if slots == self.slots:
            return self.slots
        with self._cond:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            if self._resizing:
                raise RuntimeError("a resize is already in progress")
            self._resizing = True
        try:
            deadline = time.time() + timeout
            while True:
                with self._cond:
                    if (not self._active() and self._admitting == 0
                            and not self._handoff):
                        break
                if time.time() > deadline:
                    raise TimeoutError(
                        "decode engine %r did not drain to idle within "
                        "%.1fs for resize" % (self.name, timeout))
                time.sleep(0.01)
            old = self.slots
            self.slots = slots
            self._slots = [_Slot() for _ in range(slots)]
            if self.paged:
                if not self._explicit_blocks:
                    self.num_blocks = slots * self.max_blocks
                self._pool = BlockAllocator(self.num_blocks,
                                            self.block_len)
            self._build_programs()
            self._publish_pool()
            _obs.record_decode_resize(self.name, old, slots)
        finally:
            with self._cond:
                self._resizing = False
                self._cond.notify_all()
        return self.slots

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _active(self):
        return [s for s in self._slots if s.request is not None]

    def _work_ready(self):
        if self.disaggregate:
            # queued requests belong to the prefill worker; the decode
            # loop acts on handoffs and active slots only
            return bool(self._handoff) or bool(self._active())
        return bool(self._queue) or bool(self._active())

    def _drained(self):
        return (not self._queue and not self._handoff
                and self._admitting == 0 and not self._active())

    def _publish_pool(self):
        if self.paged:
            with self._cond:
                free = self._pool.num_free
            _obs.set_kv_pool(self.name, self._pool.num_blocks, free)

    def _blocks_for(self, req):
        """Blocks to reserve at admission: the whole prompt plus the
        full generation budget, all-or-nothing — a short pool delays
        the request, it never truncates it."""
        rows = min(int(req.prompt.size) + self.config.max_new_tokens,
                   self.max_len)
        return blocks_needed(rows, self.block_len)

    def _fail_all(self, exc):
        with self._cond:  # fail everything pending; never strand a
            self._closed = True                            # caller
            pending = self._queue
            self._queue = []
            pending.extend(rec[0] for rec in self._handoff)
            self._handoff = []
            self._cond.notify_all()
        for s in self._slots:
            if s.request is not None:
                pending.append(s.request)
                s.request = None
        for r in pending:
            if not r.done():
                r._fail(exc)
                self._count("failed")

    def _loop(self):
        try:
            while True:
                with self._cond:
                    while not self._work_ready():
                        if self._closed and self._drained():
                            return
                        self._cond.wait(0.05)
                self._admit()
                if self._active():
                    self._step()
                elif self._resizing:
                    # admissions are held while a resize drains; yield
                    # so the resizer sees the idle point promptly
                    time.sleep(0.005)
        except Exception as exc:  # noqa: BLE001
            self._fail_all(exc)

    def _run_prefill(self, req, table=None, slot=None):
        """Run the bucketed prefill program for ``req``; returns the
        first sampled token.  Ring mode feeds the slot index, paged
        mode the block table."""
        L = self.buckets.bucket_for_seq(req.prompt.size)
        padded = np.zeros((1, L), dtype="int32")
        padded[0, :req.prompt.size] = req.prompt
        main, fetch = self._prefill[L]
        feed = {"prompt_ids": padded,
                "prompt_len": np.asarray([req.prompt.size], "int32")}
        attrs = dict(tenant=self.name, bucket=L,
                     prompt_len=int(req.prompt.size))
        if self.paged:
            feed["block_table"] = table.reshape(1, self.max_blocks)
            attrs["blocks"] = int((table >= 0).sum())
        else:
            feed["slot"] = np.asarray([slot], "int32")
        if slot is not None:
            attrs["slot"] = slot
        with _tr.span("serving.prefill", parent=req.span, **attrs):
            with self._exe_lock:
                out = self._exe.run(main, feed=feed,
                                    fetch_list=[fetch],
                                    scope=self.scope)
        return int(np.asarray(out[0]).reshape(-1)[0])

    def _activate(self, free, req, first, blocks, table):
        with self._cond:
            slot = self._slots[free]
            slot.request = req
            slot.cursor = int(req.prompt.size)
            slot.tokens = [first]
            slot.finished = (self.config.eos_id is not None
                             and first == self.config.eos_id)
            slot.blocks = blocks
            slot.table = table
            self._cond.notify_all()

    def _admit(self):
        """Fill free cache blocks from the queue: one prefill run per
        admission, between decode steps — the other slots' caches and
        cursors are untouched.  Disaggregated mode instead drains the
        prefill worker's finished handoffs into free slots."""
        if self.disaggregate:
            self._drain_handoffs()
            return
        while True:
            free = next((i for i, s in enumerate(self._slots)
                         if s.request is None), None)
            with self._cond:
                if self._resizing or free is None or not self._queue:
                    return
                if self.paged:
                    need = self._blocks_for(self._queue[0])
                    if not self._pool.can_allocate(need):
                        return  # backpressure: wait for a retirement
                    blocks = self._pool.allocate(need)
                else:
                    blocks = []
                req = self._queue.pop(0)
                self._admitting += 1
            table = build_block_table(blocks, self.max_blocks) \
                if self.paged else None
            first = self._run_prefill(req, table=table, slot=free)
            req.first_token_ts = time.time()
            self._activate(free, req, first, blocks, table)
            with self._cond:
                self._admitting -= 1
                self._cond.notify_all()
            self._publish_pool()

    def _drain_handoffs(self):
        """Activate finished prefills: ownership of the KV-pool blocks
        transfers from the prefill tenant to a decode slot — the K/V
        rows themselves never move (zero-copy handoff)."""
        while True:
            free = next((i for i, s in enumerate(self._slots)
                         if s.request is None), None)
            with self._cond:
                if free is None or not self._handoff:
                    return
                req, blocks, table, first, ready_ts = \
                    self._handoff.pop(0)
            wait_ms = (time.time() - ready_ts) * 1000.0
            _tr.start_span("serving.kv_handoff", parent=req.span,
                           start_ts=ready_ts, tenant=self.name,
                           slot=free, blocks=len(blocks)).end(
                dur_ms=wait_ms)
            _obs.record_kv_handoff(self.name, wait_ms, len(blocks))
            self._activate(free, req, first, blocks, table)

    def _prefill_loop(self):
        """Disaggregated-prefill worker: its own thread, its own
        program family, shared scope.  Allocates the request's blocks,
        prefills through the block table, then posts the handoff
        record for the decode scheduler to activate."""
        try:
            while True:
                with self._cond:
                    while True:
                        if self._closed and not self._queue:
                            return
                        if (self._queue and not self._resizing
                                and self._pool.can_allocate(
                                    self._blocks_for(self._queue[0]))):
                            break
                        self._cond.wait(0.05)
                    req = self._queue.pop(0)
                    blocks = self._pool.allocate(self._blocks_for(req))
                    self._admitting += 1
                table = build_block_table(blocks, self.max_blocks)
                first = self._run_prefill(req, table=table)
                req.first_token_ts = time.time()
                with self._cond:
                    self._handoff.append((req, blocks, table, first,
                                          time.time()))
                    self._admitting -= 1
                    self._cond.notify_all()
                self._publish_pool()
        except Exception as exc:  # noqa: BLE001
            self._fail_all(exc)

    def _step(self):
        """One decode step for every active slot (one jit signature),
        then retire finished requests so their cache blocks free up."""
        cur = np.zeros((self.slots,), dtype="int32")
        cursors = np.zeros((self.slots,), dtype="int32")
        active = []
        for i, s in enumerate(self._slots):
            if s.request is not None and not s.finished:
                cur[i] = s.tokens[-1]
                cursors[i] = s.cursor
                active.append(i)
        if active:
            feed = {"cur_ids": cur, "cursors": cursors}
            if self.paged:
                tables = np.full((self.slots, self.max_blocks), -1,
                                 dtype="int32")
                for i in active:
                    tables[i] = self._slots[i].table
                feed["block_tables"] = tables
            self._step_count += 1
            feed["step"] = np.asarray([self._step_count], "int32")
            with _tr.span("serving.decode_step", tenant=self.name,
                          step=self._step_count, active=len(active)):
                with self._exe_lock:
                    out = self._exe.run(
                        self._step_prog, feed=feed,
                        fetch_list=[self._step_fetch],
                        scope=self.scope)
            nxt = np.asarray(out[0]).reshape(-1)
            now = time.time()
            if self._rate_t0 is None:
                self._rate_t0 = now
            self._tokens_done += len(active)
            self._count("tokens", len(active))
            _obs.record_decode_tokens(self.name, len(active))
            span_s = now - self._rate_t0
            if span_s > 0:
                _obs.set_decode_throughput(self._tokens_done / span_s)
            for i in active:
                s = self._slots[i]
                tok = int(nxt[i])
                s.tokens.append(tok)
                s.cursor += 1
                if self.config.eos_id is not None \
                        and tok == self.config.eos_id:
                    s.finished = True
        # retire: eos, generation budget, or cache depth exhausted
        for s in self._slots:
            if s.request is None:
                continue
            full = (len(s.tokens) >= self.config.max_new_tokens
                    or s.cursor >= self.max_len - 1)
            if s.finished or full:
                req = s.request
                s.request = None
                if self.paged and s.blocks:
                    with self._cond:
                        self._pool.free(s.blocks)
                        self._cond.notify_all()  # wake admission
                    s.blocks = []
                    s.table = None
                    self._publish_pool()
                # retroactive per-request decode span (first token →
                # done) so `tools.trace --serving` splits the request's
                # critical path into prefill vs decode
                if req.first_token_ts is not None:
                    _tr.start_span(
                        "serving.decode", parent=req.span,
                        start_ts=req.first_token_ts, tenant=self.name,
                        tokens=len(s.tokens)).end(
                        dur_ms=(time.time()
                                - req.first_token_ts) * 1000.0)
                req._complete(s.tokens)
                self._count("completed")
                _obs.record_decode_request(
                    self.name, len(s.tokens),
                    ttft_ms=req.info.get("ttft_ms"))
                _obs.record_serving_done(self.name,
                                         req.info["latency_ms"])

    def _count(self, key, n=1):
        with self.stats_lock:
            self._counts[key] += n

    def stats(self):
        with self.stats_lock:
            counts = dict(self._counts)
        with self._cond:
            counts["queue_depth"] = len(self._queue)
            counts["handoff_depth"] = len(self._handoff)
            free = self._pool.num_free if self.paged else 0
        counts["active_slots"] = len(self._active())
        counts["slots"] = self.slots
        counts["prompt_buckets"] = list(self.buckets.seq_sizes)
        counts["decode_steps"] = self._step_count
        counts["paged"] = self.paged
        counts["disaggregated"] = self.disaggregate
        counts["kv_cache_bytes"] = self.cache_bytes
        if self.paged:
            counts["block_len"] = self.block_len
            counts["kv_blocks_total"] = self._pool.num_blocks
            counts["kv_blocks_free"] = free
            counts["kv_pool_occupancy"] = \
                1.0 - free / float(self._pool.num_blocks)
        return counts
