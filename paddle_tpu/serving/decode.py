"""Continuous-batching autoregressive decode engine (the serving side
of the ISSUE-14 tentpole).

One resident KV cache per layer, shape ``[slots, H, Tmax, Dh]`` with a
per-slot integer cursor (``per_row=True`` writes/reads), is carved into
``slots`` independent cache blocks.  Requests flow through two program
families that share the caches by (persistable) var name in the
engine's private Scope:

* **prefill** — one program per prompt-length bucket (the
  :mod:`~paddle_tpu.serving.buckets` seq axis): feeds one request's
  padded ``[1, L]`` prompt plus its slot index, writes K/V rows
  ``[0, plen)`` into that slot's cache block and returns the first
  sampled token.  Runs whenever a slot is FREE and a request is queued
  — admission happens mid-stream, between decode steps, without
  touching the other slots' state.
* **decode step** — ONE program for all slots: feeds the current token
  and cursor per slot, ring-writes K/V at each slot's own depth,
  flash-decode-attends masked to each slot's cursor, samples the next
  token per slot.  Every step is the same feed signature, so the jit
  cache holds exactly one entry for the whole steady state regardless
  of how long any request has been generating.

The scheduler thread interleaves the two: step the active slots, drain
finished requests, admit queued requests into the freed cache blocks,
repeat.  The per-step host hop (the sampled ``[slots]`` token vector)
is the admission decision — the device work itself stays one compiled
program.  Telemetry: ``serving_decode_tokens_total``,
``serving_generated_len`` / ``serving_ttft_ms`` histograms and the
``decode_tokens_per_sec`` gauge (``tools.monitor``), plus
``serving.prefill`` / ``serving.decode`` spans so ``tools.trace
--serving`` attributes time between the two phases.
"""

import threading
import time

import numpy as np

from ..observability import runtime as _obs
from ..observability import tracing as _tr
from .buckets import ShapeBuckets

__all__ = ["DecodeEngine", "DecodeRequest", "GenerationConfig"]


class GenerationConfig:
    """Sampling knobs a decode tenant applies to every request."""

    __slots__ = ("strategy", "k", "p", "temperature", "seed",
                 "max_new_tokens", "eos_id")

    def __init__(self, strategy="greedy", k=8, p=0.9, temperature=1.0,
                 seed=0, max_new_tokens=64, eos_id=None):
        self.strategy = strategy
        self.k = int(k)
        self.p = float(p)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id


class DecodeRequest:
    """One generation request: a future resolving to
    ``(tokens, info)`` — the generated ids (eos included when hit) and
    ``{"generated_len", "ttft_ms", "latency_ms"}``."""

    __slots__ = ("id", "prompt", "enqueue_ts", "_event", "_tokens",
                 "_error", "info", "span", "first_token_ts")

    def __init__(self, rid, prompt):
        self.id = rid
        self.prompt = prompt
        self.enqueue_ts = time.time()
        self._event = threading.Event()
        self._tokens = None
        self._error = None
        self.info = {}
        self.span = _tr.NULL_SPAN
        self.first_token_ts = None

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("decode request %r not completed within "
                               "%ss" % (self.id, timeout))
        if self._error is not None:
            raise self._error
        return self._tokens, self.info

    def _complete(self, tokens):
        self._tokens = list(tokens)
        self.info["generated_len"] = len(self._tokens)
        self.info["latency_ms"] = (time.time()
                                   - self.enqueue_ts) * 1000.0
        if self.first_token_ts is not None:
            self.info["ttft_ms"] = (self.first_token_ts
                                    - self.enqueue_ts) * 1000.0
        self.span.set_attr("generated_len", len(self._tokens))
        self.span.end("ok")
        self._event.set()

    def _fail(self, exc):
        self._error = exc
        self.span.end("error:%s" % type(exc).__name__)
        self._event.set()


class _Slot:
    __slots__ = ("request", "cursor", "tokens", "finished")

    def __init__(self):
        self.request = None   # None == free cache block
        self.cursor = 0
        self.tokens = []
        self.finished = False


class DecodeEngine:
    """The decode tenant a :class:`PredictorServer` serves.

    ``model`` supplies the two graph builders (sharing parameters by
    ParamAttr name):

    * ``model.build_prefill(prompt, plen, slot, caches) -> logits`` —
      prompt ``[1, L]`` ids, ``plen``/``slot`` ``[1]`` int32; must write
      the prompt's K/V into cache row ``slot`` (``kv_cache_prefill``
      with ``slot=``) and return the LAST real position's logits
      ``[1, V]``.
    * ``model.build_step(cur, cursors, caches) -> logits`` — ``cur``
      ``[slots]`` ids, ``cursors`` ``[slots]`` int32 (each slot's own
      depth); per-row ring-write + flash-decode; logits ``[slots, V]``.

    plus ``model.cache_spec() -> (layers, heads, max_len, head_dim)``
    and optionally ``model.init_params(program, startup, exe, scope)``
    to load/initialize weights (called once inside the engine scope).
    """

    def __init__(self, model, slots=2, prompt_buckets=(32,),
                 config=None, place=None, name="decode",
                 auto_start=True):
        import paddle_tpu as fluid
        from ..executor import Scope

        self.name = name
        self.model = model
        self.slots = int(slots)
        self.config = config or GenerationConfig()
        self.buckets = ShapeBuckets((1,), seq_sizes=prompt_buckets)
        self.scope = Scope()
        self.place = place if place is not None else fluid.TPUPlace()
        self._exe = fluid.Executor(self.place)
        self._layers, self._heads, self.max_len, self._head_dim = \
            model.cache_spec()
        self._cache_names = []
        for li in range(self._layers):
            self._cache_names.append(("%s.kcache.%d" % (name, li),
                                      "%s.vcache.%d" % (name, li)))
        self._slots = [_Slot() for _ in range(self.slots)]
        self._queue = []
        self._cond = threading.Condition()
        self._running = False
        self._closed = False
        self._resizing = False
        self._admitting = 0
        self._step_count = 0
        self._tokens_done = 0
        self._rate_t0 = None
        self.stats_lock = threading.Lock()
        self._counts = {"submitted": 0, "completed": 0, "failed": 0,
                        "tokens": 0}
        self._build_programs()
        if auto_start:
            self.start()

    # ------------------------------------------------------------------
    # graph construction
    # ------------------------------------------------------------------

    def _declare_caches(self, block):
        """Declare the persistable resident caches in ``block``'s
        program — every program family names the SAME vars, so they
        alias one buffer in the engine scope."""
        caches = []
        for kn, vn in self._cache_names:
            shape = [self.slots, self._heads, self.max_len,
                     self._head_dim]
            k = block.create_var(name=kn, shape=shape, dtype="float32",
                                 persistable=True)
            v = block.create_var(name=vn, shape=shape, dtype="float32",
                                 persistable=True)
            caches.append((k, v))
        return caches

    def _build_programs(self):
        import paddle_tpu as fluid

        cfg = self.config
        fluid.unique_name.switch()

        # init: zero the caches + the model's parameter init
        init = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(init, startup):
            for k, v in self._declare_caches(init.global_block()):
                fluid.layers.fill_constant(
                    [self.slots, self._heads, self.max_len,
                     self._head_dim], "float32", 0.0, out=k)
                fluid.layers.fill_constant(
                    [self.slots, self._heads, self.max_len,
                     self._head_dim], "float32", 0.0, out=v)

        # prefill: one program per prompt-length bucket
        self._prefill = {}
        for L in self.buckets.seq_sizes:
            main = fluid.Program()
            with fluid.program_guard(main, startup):
                prompt = fluid.layers.data(
                    "prompt_ids", shape=[1, L], dtype="int32",
                    append_batch_size=False)
                plen = fluid.layers.data(
                    "prompt_len", shape=[1], dtype="int32",
                    append_batch_size=False)
                slot = fluid.layers.data(
                    "slot", shape=[1], dtype="int32",
                    append_batch_size=False)
                caches = self._declare_caches(main.global_block())
                logits = self.model.build_prefill(prompt, plen, slot,
                                                  caches)
                first = fluid.layers.sampling(
                    logits, strategy=cfg.strategy, k=cfg.k, p=cfg.p,
                    temperature=cfg.temperature, seed=cfg.seed)
            self._prefill[L] = (main, first.name)

        # decode step: ONE program, all slots
        main = fluid.Program()
        with fluid.program_guard(main, startup):
            cur = fluid.layers.data("cur_ids", shape=[self.slots],
                                    dtype="int32",
                                    append_batch_size=False)
            cursors = fluid.layers.data("cursors", shape=[self.slots],
                                        dtype="int32",
                                        append_batch_size=False)
            step = fluid.layers.data("step", shape=[1], dtype="int32",
                                     append_batch_size=False)
            caches = self._declare_caches(main.global_block())
            logits = self.model.build_step(cur, cursors, caches)
            nxt = fluid.layers.sampling(
                logits, strategy=cfg.strategy, k=cfg.k, p=cfg.p,
                temperature=cfg.temperature, seed=cfg.seed, step=step)
        self._step_prog, self._step_fetch = main, nxt.name
        #: the program PredictorServer stamps/verifies as the hot loop
        self.program = main

        self._exe.run(startup, scope=self.scope)
        self._exe.run(init, scope=self.scope)
        init_params = getattr(self.model, "init_params", None)
        if init_params is not None:
            init_params(self._step_prog, startup, self._exe, self.scope)

    # the PredictorServer tenant-introspection surface
    def get_input_names(self):
        return ["prompt_ids"]

    def get_output_names(self):
        return [self._step_fetch]

    # ------------------------------------------------------------------
    # client side
    # ------------------------------------------------------------------

    def submit(self, prompt, request_id=None):
        """Enqueue one prompt (1-D int array); returns the
        :class:`DecodeRequest` future."""
        prompt = np.asarray(prompt, dtype="int32").reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size > self.max_len - 1:
            raise ValueError(
                "prompt of %d tokens exceeds the cache depth %d"
                % (prompt.size, self.max_len))
        if self.buckets.bucket_for_seq(prompt.size) is None:
            raise ValueError(
                "prompt of %d tokens exceeds the largest prompt "
                "bucket (%d)" % (prompt.size,
                                 self.buckets.seq_sizes[-1]))
        with self._cond:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            rid = request_id if request_id is not None \
                else len(self._queue) + self._counts["submitted"]
            req = DecodeRequest(rid, prompt)
            req.span = _tr.start_span("serving.request",
                                      tenant=self.name, request_id=rid,
                                      prompt_len=int(prompt.size))
            self._queue.append(req)
            self._count("submitted")
            self._cond.notify()
        _obs.record_serving_request(self.name)
        return req

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------

    def start(self):
        with self._cond:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="paddle_tpu-decode-%s" % self.name)
        self._thread.start()
        return self

    def close(self, timeout=60.0):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        t = getattr(self, "_thread", None)
        if t is not None:
            t.join(timeout)
            self._thread = None

    def resize(self, slots, timeout=60.0):
        """Scale the KV-cache slot count in place — the autoscaler's
        serving actuator.  Drain-to-idle semantics: admissions are held
        (queued requests stay queued), in-flight generations run to
        completion, then the cache buffers and both program families
        are rebuilt at the new count and the scheduler resumes.  No
        per-slot state needs migrating because only FREE slots exist at
        the rebuild point."""
        slots = int(slots)
        if slots < 1:
            raise ValueError("slots must be >= 1, got %d" % slots)
        if slots == self.slots:
            return self.slots
        with self._cond:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            if self._resizing:
                raise RuntimeError("a resize is already in progress")
            self._resizing = True
        try:
            deadline = time.time() + timeout
            while True:
                with self._cond:
                    if not self._active() and self._admitting == 0:
                        break
                if time.time() > deadline:
                    raise TimeoutError(
                        "decode engine %r did not drain to idle within "
                        "%.1fs for resize" % (self.name, timeout))
                time.sleep(0.01)
            old = self.slots
            self.slots = slots
            self._slots = [_Slot() for _ in range(slots)]
            self._build_programs()
            _obs.record_decode_resize(self.name, old, slots)
        finally:
            with self._cond:
                self._resizing = False
                self._cond.notify_all()
        return self.slots

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _active(self):
        return [s for s in self._slots if s.request is not None]

    def _loop(self):
        try:
            while True:
                with self._cond:
                    while (not self._closed and not self._queue
                           and not self._active()):
                        self._cond.wait(0.05)
                    if (self._closed and not self._queue
                            and not self._active()):
                        return
                self._admit()
                if self._active():
                    self._step()
                elif self._resizing:
                    # admissions are held while a resize drains; yield
                    # so the resizer sees the idle point promptly
                    time.sleep(0.005)
        except Exception as exc:  # noqa: BLE001 — fail everything
            with self._cond:     # pending; never strand a caller
                self._closed = True
                pending = self._queue
                self._queue = []
            for s in self._slots:
                if s.request is not None:
                    pending.append(s.request)
                    s.request = None
            for r in pending:
                if not r.done():
                    r._fail(exc)
                    self._count("failed")

    def _admit(self):
        """Fill free cache blocks from the queue: one prefill run per
        admission, between decode steps — the other slots' caches and
        cursors are untouched (their rows in the [slots, ...] buffer
        are not written by this slot's kv_cache_prefill)."""
        while True:
            free = next((i for i, s in enumerate(self._slots)
                         if s.request is None), None)
            with self._cond:
                if self._resizing or free is None or not self._queue:
                    return
                req = self._queue.pop(0)
                self._admitting += 1
            L = self.buckets.bucket_for_seq(req.prompt.size)
            padded = np.zeros((1, L), dtype="int32")
            padded[0, :req.prompt.size] = req.prompt
            main, fetch = self._prefill[L]
            with _tr.span("serving.prefill", parent=req.span,
                          tenant=self.name, slot=free, bucket=L,
                          prompt_len=int(req.prompt.size)):
                out = self._exe.run(
                    main,
                    feed={"prompt_ids": padded,
                          "prompt_len": np.asarray([req.prompt.size],
                                                   "int32"),
                          "slot": np.asarray([free], "int32")},
                    fetch_list=[fetch], scope=self.scope)
            first = int(np.asarray(out[0]).reshape(-1)[0])
            req.first_token_ts = time.time()
            with self._cond:
                slot = self._slots[free]
                slot.request = req
                slot.cursor = int(req.prompt.size)
                slot.tokens = [first]
                slot.finished = (self.config.eos_id is not None
                                 and first == self.config.eos_id)
                self._admitting -= 1
                self._cond.notify_all()

    def _step(self):
        """One decode step for every active slot (one jit signature),
        then retire finished requests so their cache blocks free up."""
        cur = np.zeros((self.slots,), dtype="int32")
        cursors = np.zeros((self.slots,), dtype="int32")
        active = []
        for i, s in enumerate(self._slots):
            if s.request is not None and not s.finished:
                cur[i] = s.tokens[-1]
                cursors[i] = s.cursor
                active.append(i)
        if active:
            self._step_count += 1
            with _tr.span("serving.decode_step", tenant=self.name,
                          step=self._step_count, active=len(active)):
                out = self._exe.run(
                    self._step_prog,
                    feed={"cur_ids": cur, "cursors": cursors,
                          "step": np.asarray([self._step_count],
                                             "int32")},
                    fetch_list=[self._step_fetch], scope=self.scope)
            nxt = np.asarray(out[0]).reshape(-1)
            now = time.time()
            if self._rate_t0 is None:
                self._rate_t0 = now
            self._tokens_done += len(active)
            self._count("tokens", len(active))
            _obs.record_decode_tokens(self.name, len(active))
            span_s = now - self._rate_t0
            if span_s > 0:
                _obs.set_decode_throughput(self._tokens_done / span_s)
            for i in active:
                s = self._slots[i]
                tok = int(nxt[i])
                s.tokens.append(tok)
                s.cursor += 1
                if self.config.eos_id is not None \
                        and tok == self.config.eos_id:
                    s.finished = True
        # retire: eos, generation budget, or cache depth exhausted
        for s in self._slots:
            if s.request is None:
                continue
            full = (len(s.tokens) >= self.config.max_new_tokens
                    or s.cursor >= self.max_len - 1)
            if s.finished or full:
                req = s.request
                s.request = None
                # retroactive per-request decode span (first token →
                # done) so `tools.trace --serving` splits the request's
                # critical path into prefill vs decode
                if req.first_token_ts is not None:
                    _tr.start_span(
                        "serving.decode", parent=req.span,
                        start_ts=req.first_token_ts, tenant=self.name,
                        tokens=len(s.tokens)).end(
                        dur_ms=(time.time()
                                - req.first_token_ts) * 1000.0)
                req._complete(s.tokens)
                self._count("completed")
                _obs.record_decode_request(
                    self.name, len(s.tokens),
                    ttft_ms=req.info.get("ttft_ms"))
                _obs.record_serving_done(self.name,
                                         req.info["latency_ms"])

    def _count(self, key, n=1):
        with self.stats_lock:
            self._counts[key] += n

    def stats(self):
        with self.stats_lock:
            counts = dict(self._counts)
        with self._cond:
            counts["queue_depth"] = len(self._queue)
        counts["active_slots"] = len(self._active())
        counts["slots"] = self.slots
        counts["prompt_buckets"] = list(self.buckets.seq_sizes)
        counts["decode_steps"] = self._step_count
        return counts
