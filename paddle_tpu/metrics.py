"""Python-side metric accumulators (reference:
``python/paddle/fluid/metrics.py``).

``update`` methods accept device arrays (e.g. un-synced fetch handles
from ``Executor.run(..., return_numpy=False)``) and convert every
argument in ONE batched device→host sync — per-value ``np.asarray``
would serialize the async dispatch queue once per argument and turn an
eval loop back into lock-step host/device alternation."""

import numpy as np

__all__ = ["MetricBase", "Accuracy", "CompositeMetric", "Precision",
           "Recall", "Auc", "ChunkEvaluator", "EditDistance"]


def _host(*values):
    """Batched device→host conversion of update() arguments (one sync
    for all of them; pure-numpy inputs pass straight through)."""
    from .pipeline import host_values

    return host_values(values)


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k in list(self.__dict__):
            if not k.startswith("_"):
                setattr(self, k, 0.0)

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no samples accumulated")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds, labels = _host(preds, labels)
        preds = np.rint(preds).astype(int).reshape(-1)
        labels = labels.astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds, labels = _host(preds, labels)
        preds = np.rint(preds).astype(int).reshape(-1)
        labels = labels.astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds, labels = _host(preds, labels)
        labels = labels.reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.minimum(
            (pos_prob * self._num_thresholds).astype(int),
            self._num_thresholds,
        )
        for i, lab in zip(idx, labels):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def eval(self):
        tot_pos = tot_neg = auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return auc / denom if denom else 0.0


class ChunkEvaluator(MetricBase):
    """Accumulates the three counters emitted by ``layers.chunk_eval``
    and reports (precision, recall, f1) (reference metrics.py:410)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        ni, nl, nc = _host(num_infer_chunks, num_label_chunks,
                           num_correct_chunks)
        self.num_infer_chunks += int(ni.sum())
        self.num_label_chunks += int(nl.sum())
        self.num_correct_chunks += int(nc.sum())

    def eval(self):
        precision = (float(self.num_correct_chunks) / self.num_infer_chunks
                     if self.num_infer_chunks else 0)
        recall = (float(self.num_correct_chunks) / self.num_label_chunks
                  if self.num_label_chunks else 0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """Accumulates per-sequence edit distances from
    ``layers.edit_distance`` and reports (avg_distance,
    wrong_instance_ratio) (reference metrics.py:492)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        (distances,) = _host(distances)
        self.seq_num += seq_num
        self.instance_error += int(seq_num - np.sum(distances == 0))
        self.total_distance += float(np.sum(distances))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError(
                "There is no data in EditDistance Metric. Please feed it "
                "layers.edit_distance outputs via update() first.")
        return (self.total_distance / self.seq_num,
                self.instance_error / float(self.seq_num))
