"""Dataset pipeline (reference: ``python/paddle/fluid/dataset.py`` facades +
C++ ``framework/data_set.h:40`` Dataset/MultiSlotDataset and
``data_feed.h`` MultiSlot parsers feeding trainer threads).

TPU-native: files are parsed into padded numpy slot batches on the host
(threaded), prefetched, and fed to the jitted step — the channel/queue
machinery of the reference maps onto the PyReader prefetcher.  MultiSlot
text format (one example per line: per slot ``<n> id...`` or
``<n> v v ...``) is parsed as in ``data_feed.cc``; ragged slots pad/clip to
the slot var's declared static length (XLA static shapes).
"""

import os
import random

import numpy as np

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset",
           "QueueDataset"]


class DatasetFactory:
    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError("unknown dataset class %r" % datafeed_class)


class DatasetBase:
    def __init__(self):
        self.proto_desc_pipe_command = "cat"
        self.batch_size = 1
        self.filelist = []
        self.use_vars = []
        self.thread_num = 1
        self.hdfs_config = None
        self._shuffle_seed = 0

    # ---- reference config surface ----
    def set_pipe_command(self, pipe_command):
        self.proto_desc_pipe_command = pipe_command

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_hdfs_config(self, fs_name, fs_ugi):
        self.hdfs_config = (fs_name, fs_ugi)

    def desc(self):
        return {
            "pipe_command": self.proto_desc_pipe_command,
            "batch_size": self.batch_size,
            "thread_num": self.thread_num,
        }

    # ---- parsing ----
    def _slot_len(self, var):
        shape = var.shape or (-1, 1)
        inner = 1
        for d in shape[1:]:
            inner *= abs(d)
        return max(inner, 1)

    def _batches_from(self, examples):
        batch = []
        for ex in examples:
            batch.append(ex)
            if len(batch) == self.batch_size:
                yield self._to_feed(batch)
                batch = []
        if batch:
            yield self._to_feed(batch)

    def _to_feed(self, batch):
        feed = {}
        for i, var in enumerate(self.use_vars):
            arr = np.stack([ex[i] for ex in batch])
            shape = var.shape or ()
            if len(shape) > 1:
                arr = arr.reshape((len(batch),) + tuple(
                    abs(d) for d in shape[1:]
                ))
            feed[var.name] = arr
        return feed

    def _native_file_arrays(self, path):
        """Parse one file with the MultiSlot parser (C++ thread pool when
        available, else its semantics-identical Python fallback —
        paddle_tpu/native) into per-slot [N, L] arrays."""
        from . import native

        types = ["uint64" if v.dtype in ("int64", "int32") else "float"
                 for v in self.use_vars]
        lens = [self._slot_len(v) for v in self.use_vars]
        return native.parse_multislot_file(path, types, lens,
                                           threads=self.thread_num)

    def _iter_examples_native(self):
        for path in self.filelist:
            arrays = self._native_file_arrays(path)
            n = arrays[0].shape[0] if arrays else 0
            for i in range(n):
                yield [a[i] for a in arrays]

    def batch_iterator(self):
        return self._batches_from(self._iter_examples_native())


class QueueDataset(DatasetBase):
    """Streams files (reference dataset.py QueueDataset)."""

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams; use InMemoryDataset for shuffling "
            "(same restriction as the reference)"
        )

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "QueueDataset streams; use InMemoryDataset for shuffling"
        )


class InMemoryDataset(DatasetBase):
    """Loads, shuffles in memory (reference dataset.py InMemoryDataset;
    global_shuffle's cross-worker exchange maps to per-worker filelist
    sharding + local shuffle on TPU pods)."""

    def __init__(self):
        super().__init__()
        self._examples = []
        self._loaded = False

    def load_into_memory(self):
        self._examples = list(self._iter_examples_native())
        self._loaded = True

    def local_shuffle(self):
        if not self._loaded:
            raise RuntimeError("call load_into_memory() first")
        random.Random(self._shuffle_seed).shuffle(self._examples)

    def global_shuffle(self, fleet=None, thread_num=12):
        if fleet is not None:
            self.filelist = fleet.split_files(self.filelist)
            self.load_into_memory()
        self.local_shuffle()

    def release_memory(self):
        self._examples = []
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        return len(self._examples)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._examples)

    def batch_iterator(self):
        if self._loaded:
            return self._batches_from(iter(self._examples))
        return super().batch_iterator()
