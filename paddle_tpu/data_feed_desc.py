"""DataFeedDesc (reference: ``python/paddle/fluid/data_feed_desc.py``) —
the text-protobuf descriptor of a MultiSlot data feed
(``framework/data_feed.proto``).

The reference parses the file with protobuf text_format into
data_feed_pb2; here a purpose-built parser reads the same text format
into plain dicts (the message is two levels deep: scalar fields +
``multi_slot_desc { slots { ... } }``), and ``desc()`` re-serializes
byte-compatibly enough for the native MultiSlot parser
(``dataset.py``)."""

__all__ = ["DataFeedDesc"]


def _parse_scalar(tok):
    t = tok.strip()
    if t.startswith('"') and t.endswith('"'):
        return t[1:-1]
    if t in ("true", "false"):
        return t == "true"
    try:
        return int(t)
    except ValueError:
        try:
            return float(t)
        except ValueError:
            return t


def _parse_block(lines, i):
    """Parse `key: value` / `key { ... }` lines until the closing '}'."""
    out = {}
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        if line == "}":
            return out, i
        if line.endswith("{"):
            key = line[:-1].strip()
            sub, i = _parse_block(lines, i)
            out.setdefault(key, []).append(sub)
        elif ":" in line:
            key, _, val = line.partition(":")
            out[key.strip()] = _parse_scalar(val)
    return out, i


def _fmt_scalar(v):
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return '"%s"' % v
    return str(v)


def _serialize(d, indent=0):
    pad = "  " * indent
    lines = []
    for k, v in d.items():
        if isinstance(v, list):
            for sub in v:
                lines.append("%s%s {" % (pad, k))
                lines.append(_serialize(sub, indent + 1))
                lines.append("%s}" % pad)
        else:
            lines.append("%s%s: %s" % (pad, k, _fmt_scalar(v)))
    return "\n".join(lines)


class DataFeedDesc:
    """Reference :82 — initialize from a proto text file, then tune
    batch size / dense / used slots before handing to a trainer."""

    def __init__(self, proto_file):
        with open(proto_file) as f:
            lines = f.read().splitlines()
        self.proto_desc, _ = _parse_block(lines, 0)
        self.proto_desc.setdefault("pipe_command", "cat")
        self._name_to_slot = {}
        for msd in self.proto_desc.get("multi_slot_desc", []):
            for slot in msd.get("slots", []):
                self._name_to_slot[slot.get("name")] = slot

    def set_batch_size(self, batch_size):
        self.proto_desc["batch_size"] = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        """Mark slots dense (fixed-shape float) — all others stay sparse
        (reference :128)."""
        if self.proto_desc.get("name") != "MultiSlotDataFeed":
            raise ValueError(
                "Only MultiSlotDataFeed needs set_dense_slots")
        for name in dense_slots_name:
            self._name_to_slot[name]["is_dense"] = True

    def set_use_slots(self, use_slots_name):
        """Mark which slots are consumed by the model (reference :173)."""
        if self.proto_desc.get("name") != "MultiSlotDataFeed":
            raise ValueError(
                "Only MultiSlotDataFeed needs set_use_slots")
        for msd in self.proto_desc.get("multi_slot_desc", []):
            for slot in msd.get("slots", []):
                slot["is_used"] = slot.get("name") in use_slots_name

    def desc(self):
        """Text-format serialization (reference :218)."""
        return _serialize(self.proto_desc) + "\n"
