"""LayerHelper: parameter creation + op appending glue used by every layer
(reference: ``python/paddle/fluid/layer_helper.py``, append_op at :42)."""

from . import unique_name
from .framework import default_main_program, default_startup_program, Variable
from .initializer import (
    ConstantInitializer,
    XavierInitializer,
    _global_bias_initializer,
    _global_weight_initializer,
)
from .param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        name = kwargs.get("name")
        if name is None:
            name = unique_name.generate(layer_type)
        self.name = name
        self.layer_type = layer_type

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    # ---- inputs ----
    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            inputs = [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" % self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr]
        if len(attr) != 1 and len(attr) != length:
            raise ValueError("parameter number mismatch")
        if len(attr) == 1 and length != 1:
            attr = [attr[0]] + [
                ParamAttr(**attr[0].__dict__) for _ in range(length - 1)
            ]
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        attrs = self.multiple_param_attr(len(inputs))
        for i, a in zip(inputs, attrs):
            yield i, a

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for i in inputs:
            if dtype is None:
                dtype = i.dtype
        return dtype

    # ---- parameters ----
    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None, stop_gradient=False):
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if default_initializer is None:
            if is_bias:
                g = _global_bias_initializer()
                attr._set_default_initializer(g or ConstantInitializer(0.0))
            else:
                g = _global_weight_initializer()
                attr._set_default_initializer(g or XavierInitializer())
        else:
            attr._set_default_initializer(default_initializer)

        if attr.name is None:
            suffix = "b" if is_bias else "w"
            attr.name = unique_name.generate(".".join([self.name, suffix]))

        shape = [int(s) for s in shape]
        startup_block = self.startup_program.global_block()
        # a re-declared shared parameter (same ParamAttr name — e.g. the
        # prefill and decode-step subgraphs of one generation program)
        # is ONE var: initialize it once, or startup double-writes the
        # buffer (a donation-aliasing hazard the lint rightly flags)
        redeclared = attr.name in startup_block.vars
        sp = startup_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs(with_initializer=False)
        )
        if not redeclared:
            attr.initializer(sp, startup_block)
        main_block = self.main_program.global_block()
        return main_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs()
        )

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            persistable=False,
            stop_gradient=stop_gradient,
        )

    # older reference spelling
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args,
            persistable=persistable,
            name=unique_name.generate(".".join([self.name, "tmp"])),
            **kwargs,
        )

    def create_or_get_global_variable(self, name, *args, **kwargs):
        block = self.main_program.global_block()
        if block.has_var(name):
            return block.var(name)
        return block.create_var(*args, name=name, persistable=True, **kwargs)

    def set_variable_initializer(self, var, initializer):
        """Create `var` in the startup program and initialize it there."""
        sb = self.startup_program.global_block()
        sv = sb.create_var(
            name=var.name, shape=var.shape, dtype=var.dtype, persistable=True
        )
        initializer(sv, sb)
        return var

    # ---- common epilogues ----
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        b = self.create_parameter(
            attr=bias_attr, shape=size, dtype=input_var.dtype, is_bias=True
        )
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        else:
            act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type=act_type,
            inputs={"X": [input_var]},
            outputs={"Out": [tmp]},
            attrs=act,
        )
        return tmp
