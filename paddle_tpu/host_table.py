"""Host-resident embedding tables — the bigger-than-HBM CTR capability
the reference's parameter server actually served.

Reference: remote prefetch of distributed lookup tables
(``paddle/fluid/operators/distributed/parameter_prefetch.cc``) and the
async push/pull ``Communicator`` threads
(``operators/distributed/communicator.h:160-179``): embedding tables too
large for accelerator memory live on host (pserver) RAM; each step
prefetches only the rows the batch touches and pushes back sparse
gradient updates asynchronously.

TPU redesign: there is no pserver RPC — the table is a numpy array in
THIS process's host RAM.  Per step the executor

1. joins the previous step's in-flight update thread (the async-push
   analogue: the host scatter-add overlaps the next device step's
   dispatch + host data prep),
2. gathers the batch's rows into a dense ``[batch..., dim]`` slab fed to
   the jitted step like any other input (MXU-friendly: the device never
   sees the table, only a small dense slab),
3. fetches the slab's gradient from the step outputs and hands it to a
   background thread that aggregates duplicate ids and applies the
   sparse optimizer update (SGD or Adagrad) on host.

Checkpoints use the SAME per-shard layout as the distributed device
checkpoint (``io.py`` ``shard-*.npy`` + ``meta.json``), so a table can
move between host-resident and device-row-sharded deployments in either
direction (reshard-on-load).
"""

import os
import threading

import numpy as np

__all__ = ["HostTable", "get_table", "get_or_create", "reset_tables"]

_TABLES = {}


def reset_tables():
    """Drop all registered tables (test isolation)."""
    for t in _TABLES.values():
        t.join()
    _TABLES.clear()


def get_table(name):
    return _TABLES[name]


def get_or_create(name, rows, dim, dtype="float32", lr=0.1,
                  optimizer="sgd", initializer=None, seed=0):
    tab = _TABLES.get(name)
    if tab is None:
        tab = HostTable(name, rows, dim, dtype=dtype, lr=lr,
                        optimizer=optimizer, initializer=initializer,
                        seed=seed)
        _TABLES[name] = tab
    elif (tab.rows, tab.dim, tab.lr, tab.optimizer) != (
            int(rows), int(dim), float(lr), optimizer):
        raise ValueError(
            "host table %r already exists with (rows=%d, dim=%d, lr=%g, "
            "optimizer=%s); requested (%d, %d, %g, %s) — call "
            "host_table.reset_tables() to rebuild"
            % (name, tab.rows, tab.dim, tab.lr, tab.optimizer,
               int(rows), int(dim), float(lr), optimizer))
    return tab


class HostTable:
    def __init__(self, name, rows, dim, dtype="float32", lr=0.1,
                 optimizer="sgd", initializer=None, seed=0):
        self.name = name
        self.rows = int(rows)
        self.dim = int(dim)
        self.lr = float(lr)
        self.optimizer = optimizer
        if optimizer not in ("sgd", "adagrad"):
            raise ValueError("host table optimizer must be sgd or adagrad")
        if initializer is not None:
            self.value = np.asarray(initializer, dtype).reshape(
                self.rows, self.dim)
        else:
            # reference lookup-table default init (uniform) — deterministic
            # per (name, seed) so every process builds the same table
            # (crc32, NOT hash(): Python hash randomization is salted
            # per process and would silently desync a multi-process
            # cluster's replicas)
            import zlib

            rng = np.random.RandomState(
                (zlib.crc32(name.encode()) ^ seed) & 0x7FFFFFFF)
            self.value = rng.uniform(
                -0.05, 0.05, (self.rows, self.dim)).astype(dtype)
        self._accum = None
        if optimizer == "adagrad":
            self._accum = np.zeros((self.rows, self.dim), "float32")
        self._pending = None

    # ---- step-time path ------------------------------------------------

    def join(self):
        """Wait for the in-flight async update (call before lookup)."""
        t = self._pending
        if t is not None:
            t.join()
            self._pending = None

    def lookup(self, ids):
        """Prefetch: dense slab of the rows this batch touches
        (parameter_prefetch.cc role).  ids any int shape; returns
        ids.shape + (dim,)."""
        self.join()
        idx = np.asarray(ids).astype(np.int64)
        flat = np.clip(idx.reshape(-1), 0, self.rows - 1)
        return self.value[flat].reshape(idx.shape + (self.dim,))

    def update_async(self, ids, slab_grad):
        """Async push (communicator.h role): background-thread sparse
        update; duplicate ids are aggregated before the optimizer rule so
        the result matches a scatter-add dense update exactly."""
        self.join()
        idx = np.clip(np.asarray(ids).astype(np.int64).reshape(-1),
                      0, self.rows - 1)
        g = np.asarray(slab_grad, np.float32).reshape(idx.shape[0],
                                                      self.dim)
        t = threading.Thread(target=self._apply, args=(idx, g),
                             daemon=True)
        self._pending = t
        t.start()

    def _apply(self, idx, g):
        uniq, inv = np.unique(idx, return_inverse=True)
        agg = np.zeros((uniq.shape[0], self.dim), np.float32)
        np.add.at(agg, inv, g)
        if self.optimizer == "sgd":
            self.value[uniq] -= (self.lr * agg).astype(self.value.dtype)
        else:  # adagrad (reference sparse adagrad_op path)
            self._accum[uniq] += agg * agg
            self.value[uniq] -= (
                self.lr * agg / (np.sqrt(self._accum[uniq]) + 1e-6)
            ).astype(self.value.dtype)

    # ---- checkpoint (shared per-shard layout with io._save_sharded) ----

    def _shard_dir(self, dirname):
        return os.path.join(dirname, self.name.replace("/", "_")
                            + ".shards")

    def has_checkpoint(self, dirname):
        return os.path.isdir(self._shard_dir(dirname))

    def save(self, dirname, rows_per_shard=None):
        """Write the table in the distributed checkpoint's shard layout:
        row-range ``shard-r0_r1-0_D.npy`` files + ``meta.json`` (+ the
        adagrad accumulator, so resume keeps the optimizer history)."""
        import json

        from .io import _shard_fname

        self.join()  # never snapshot mid-async-update
        shard_dir = self._shard_dir(dirname)
        os.makedirs(shard_dir, exist_ok=True)
        step = int(rows_per_shard or max(1, min(self.rows, 1 << 20)))
        files = []
        for r0 in range(0, self.rows, step):
            r1 = min(r0 + step, self.rows)
            bounds = ((r0, r1), (0, self.dim))
            fname = _shard_fname(bounds)
            np.save(os.path.join(shard_dir, fname), self.value[r0:r1])
            files.append(fname)
        if self._accum is not None:
            np.save(os.path.join(shard_dir, "adagrad_accum.npy"),
                    self._accum)
        meta_tmp = os.path.join(shard_dir,
                                ".meta.json.tmp.%d" % os.getpid())
        with open(meta_tmp, "w") as f:
            json.dump({"shape": [self.rows, self.dim],
                       "dtype": str(self.value.dtype),
                       "files": files}, f)
        os.replace(meta_tmp, os.path.join(shard_dir, "meta.json"))

    def load(self, dirname):
        """Reshard-on-load from ANY shard layout of the same global
        table — one written by HostTable.save or by the device-sharded
        checkpoint path (io._save_sharded)."""
        import json

        from .io import _read_sharded_region, _shard_entries

        self.join()
        shard_dir = self._shard_dir(dirname)
        with open(os.path.join(shard_dir, "meta.json")) as f:
            meta = json.load(f)
        if list(meta["shape"]) != [self.rows, self.dim]:
            raise ValueError(
                "checkpointed table %s has shape %s, expected %s"
                % (self.name, meta["shape"], [self.rows, self.dim]))
        entries = _shard_entries(shard_dir, meta)
        self.value = np.asarray(_read_sharded_region(
            entries, meta, ((0, self.rows), (0, self.dim)), self.name),
            dtype=self.value.dtype)
        if self._accum is not None:
            apath = os.path.join(shard_dir, "adagrad_accum.npy")
            # a checkpoint written by the device path has no accumulator
            # file: restart the history from zeros rather than mixing the
            # stale in-memory one with the freshly loaded values
            self._accum = (np.load(apath) if os.path.exists(apath)
                           else np.zeros((self.rows, self.dim), "float32"))
