"""BERT/Transformer-encoder pretraining model (BASELINE config 3; reference
analogue: the transformer benchmark ``benchmark/fluid/models/``,
attention built like ``python/paddle/fluid/nets.py`` scaled-dot-product).

TPU design: every projection is an MXU-shaped matmul via `fc` with
num_flatten_dims=2 (so [B,T,D]x[D,K] batched GEMMs); the attention mask is
an additive [-inf] bias broadcast over heads; AMP (bf16 rewrite,
contrib.mixed_precision) turns all of these into bf16 MXU matmuls with fp32
master weights."""

import math

import paddle_tpu as fluid


class BertConfig:
    def __init__(self, vocab_size=30522, hidden=768, layers=12, heads=12,
                 ffn=3072, max_seq=512, type_vocab=2, dropout=0.1,
                 attn_dropout=None, fuse_attn="auto", recompute=False,
                 fused_qkv=False, fused_ln=False):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.heads = heads
        self.ffn = ffn
        self.max_seq = max_seq
        self.type_vocab = type_vocab
        self.dropout = dropout
        # attention-probability dropout; the fused flash-attention path
        # requires 0 (as in production TPU flash attention), so configs
        # that want the fused kernel set attn_dropout=0
        self.attn_dropout = dropout if attn_dropout is None else attn_dropout
        # "auto" (default): route by sequence length — the unfused
        # matmul/softmax/dropout chain below the flash threshold (XLA's
        # own fusion beat the fused op's fallback by +7.6% at T=128 on
        # v5e), fused_multihead_attention at/above it (the Pallas flash
        # kernel beat XLA fusion by +14.6% at T=512).  True/False force
        # one path (the r05 hardware A/B knobs).
        self.fuse_attn = fuse_attn
        # one 3d-wide QKV projection GEMM per layer instead of three
        # d-wide ones (see _attention); opt-in, changes param layout
        self.fused_qkv = fused_qkv
        # route the encoder's dropout+residual+layer_norm glue through
        # the fused Pallas op (layers.fused_dropout_add_ln) — one VMEM
        # pass instead of three HBM-bound ops; opt-in pending hardware A/B
        self.fused_ln = fused_ln
        # wrap each encoder layer in fluid.layers.recompute() — backward
        # re-runs the layer instead of keeping its activations (the
        # long-sequence memory lever; one extra forward per layer)
        self.recompute = recompute


BERT_BASE = BertConfig()
BERT_TINY = BertConfig(vocab_size=1024, hidden=128, layers=2, heads=2,
                       ffn=512, max_seq=128)


def _attention(x, mask_bias, cfg, prefix):
    d = cfg.hidden
    dh = d // cfg.heads

    def proj(inp, size, name):
        return fluid.layers.fc(
            inp, size=size, num_flatten_dims=2,
            param_attr=fluid.ParamAttr(name=prefix + "." + name + ".w"),
            bias_attr=fluid.ParamAttr(name=prefix + "." + name + ".b"),
        )

    def split_heads(t):
        t = fluid.layers.reshape(t, [0, 0, cfg.heads, dh])
        return fluid.layers.transpose(t, [0, 2, 1, 3])

    if getattr(cfg, "fused_qkv", False):
        # one [*, d]x[d, 3d] GEMM instead of three [d, d] GEMMs: fewer,
        # wider MXU launches (N=2304 amortizes weight loads the three
        # N=768 launches each pay).  Parameter layout differs from the
        # per-projection form (one .qkv.w), hence opt-in.
        qkv = proj(x, 3 * d, "qkv")
        q = split_heads(fluid.layers.slice(qkv, [2], [0], [d]))
        k = split_heads(fluid.layers.slice(qkv, [2], [d], [2 * d]))
        v = split_heads(fluid.layers.slice(qkv, [2], [2 * d], [3 * d]))
    else:
        q = split_heads(proj(x, d, "q"))
        k = split_heads(proj(x, d, "k"))
        v = split_heads(proj(x, d, "v"))
    fuse = cfg.fuse_attn
    if fuse == "auto":
        # static [B, H, T, dh] shape: route by T against the flash
        # engagement threshold so "auto" always picks the measured
        # winner (unfused chain below it, Pallas kernel at/above)
        from paddle_tpu.ops.pallas.flash_attention import flash_min_t

        fuse = int(q.shape[2]) >= flash_min_t()
    if fuse:
        ctx = fluid.layers.fused_multihead_attention(
            q, k, v, bias=mask_bias, scale=1.0 / math.sqrt(dh),
            dropout_rate=cfg.attn_dropout or 0.0,
        )
    else:
        scores = fluid.layers.matmul(q, k, transpose_y=True,
                                     alpha=1.0 / math.sqrt(dh))
        if mask_bias is not None:
            scores = fluid.layers.elementwise_add(scores, mask_bias)
        probs = fluid.layers.softmax(scores)
        if cfg.attn_dropout:
            probs = fluid.layers.dropout(
                probs, cfg.attn_dropout,
                dropout_implementation="upscale_in_train"
            )
        ctx = fluid.layers.matmul(probs, v)
    ctx = fluid.layers.transpose(ctx, [0, 2, 1, 3])
    ctx = fluid.layers.reshape(ctx, [0, 0, d])
    return proj(ctx, d, "o")


def _sublayer_close(x, sub, cfg, ln_name):
    """The encoder's inter-GEMM glue, ``layer_norm(x + dropout(sub))``:
    either the three-op chain (XLA fuses what it can) or the single
    fused Pallas op (cfg.fused_ln) — identical math, same LN param
    names/shapes either way."""
    if cfg.fused_ln:
        return fluid.layers.fused_dropout_add_ln(
            sub, x, dropout_prob=cfg.dropout or 0.0,
            param_attr=fluid.ParamAttr(name=ln_name + ".scale"),
            bias_attr=fluid.ParamAttr(name=ln_name + ".bias"),
        )
    if cfg.dropout:
        sub = fluid.layers.dropout(
            sub, cfg.dropout, dropout_implementation="upscale_in_train"
        )
    return fluid.layers.layer_norm(
        fluid.layers.elementwise_add(x, sub), begin_norm_axis=2,
        param_attr=fluid.ParamAttr(name=ln_name + ".scale"),
        bias_attr=fluid.ParamAttr(name=ln_name + ".bias"),
    )


def _encoder_layer(x, mask_bias, cfg, prefix):
    attn = _attention(x, mask_bias, cfg, prefix + ".attn")
    x = _sublayer_close(x, attn, cfg, prefix + ".ln1")
    ff = fluid.layers.fc(
        x, size=cfg.ffn, num_flatten_dims=2, act="gelu",
        param_attr=fluid.ParamAttr(name=prefix + ".ffn1.w"),
        bias_attr=fluid.ParamAttr(name=prefix + ".ffn1.b"),
    )
    ff = fluid.layers.fc(
        ff, size=cfg.hidden, num_flatten_dims=2,
        param_attr=fluid.ParamAttr(name=prefix + ".ffn2.w"),
        bias_attr=fluid.ParamAttr(name=prefix + ".ffn2.b"),
    )
    return _sublayer_close(x, ff, cfg, prefix + ".ln2")


def encoder(input_ids, token_type_ids, attn_mask_bias, cfg, seq_len):
    """[B,T] ids → [B,T,D] hidden states."""
    init = fluid.initializer.TruncatedNormal(scale=0.02)
    word_emb = fluid.layers.embedding(
        input_ids, size=[cfg.vocab_size, cfg.hidden],
        param_attr=fluid.ParamAttr(name="bert.word_emb", initializer=init),
    )
    pos_ids = fluid.layers.data("pos_ids", shape=[seq_len], dtype="int64")
    pos_emb = fluid.layers.embedding(
        pos_ids, size=[cfg.max_seq, cfg.hidden],
        param_attr=fluid.ParamAttr(name="bert.pos_emb", initializer=init),
    )
    type_emb = fluid.layers.embedding(
        token_type_ids, size=[cfg.type_vocab, cfg.hidden],
        param_attr=fluid.ParamAttr(name="bert.type_emb", initializer=init),
    )
    x = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(word_emb, pos_emb), type_emb
    )
    x = fluid.layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=fluid.ParamAttr(name="bert.emb_ln.scale"),
        bias_attr=fluid.ParamAttr(name="bert.emb_ln.bias"),
    )
    if cfg.dropout:
        x = fluid.layers.dropout(
            x, cfg.dropout, dropout_implementation="upscale_in_train"
        )
    for i in range(cfg.layers):
        if cfg.recompute:
            with fluid.layers.recompute():
                x = _encoder_layer(x, attn_mask_bias, cfg,
                                   "bert.layer%d" % i)
        else:
            x = _encoder_layer(x, attn_mask_bias, cfg, "bert.layer%d" % i)
    return x


def default_max_pred(seq_len):
    """Masked positions the MLM head scores per sequence — the single
    source of truth shared by build_pretrain, make_fake_batch, and
    bench.py's MFU denominator (they must agree on the gather layout)."""
    return int(0.15 * seq_len) + 1


def build_pretrain(cfg=BERT_BASE, seq_len=128, lr=1e-4, amp=False,
                   train=True, max_pred=None):
    """Masked-LM pretraining program.  Returns
    (main, startup, feed_names, loss).  With train=False only the forward
    loss graph is built (no grad/optimizer ops).

    max_pred: how many masked positions per sequence the MLM head scores.
    Default ``int(0.15 * seq_len) + 1`` — the reference-era BERT recipe
    gathers the masked positions (fed as flattened ``mask_pos`` indices)
    BEFORE the vocab projection, so the [positions, V] logits cover only
    ~15% of tokens instead of all of them; the vocab head is ~20% of the
    step's FLOPs at seq128, so scoring every position wastes real MXU
    time and logits bandwidth.  Pass ``max_pred=0`` for the legacy
    all-position head."""
    if max_pred is None:
        max_pred = default_max_pred(seq_len)
    if not train:
        # inference graph: ALL dropout off (hidden + attention-prob) —
        # the eval program must be deterministic run-to-run
        import copy

        cfg = copy.copy(cfg)
        cfg.attn_dropout = 0.0
        cfg.dropout = 0.0
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        input_ids = fluid.layers.data("input_ids", shape=[seq_len],
                                      dtype="int64")
        token_type = fluid.layers.data("token_type_ids", shape=[seq_len],
                                       dtype="int64")
        # additive mask bias, [B,1,1,T]: 0 keep / -1e4 drop
        mask_bias = fluid.layers.data(
            "attn_mask_bias", shape=[1, 1, seq_len], dtype="float32"
        )
        n_pred = max_pred or seq_len
        mlm_labels = fluid.layers.data("mlm_labels", shape=[n_pred],
                                       dtype="int64")
        mlm_weights = fluid.layers.data("mlm_weights", shape=[n_pred],
                                        dtype="float32")
        if max_pred:
            # catch callers still feeding the legacy all-position
            # [seq_len] layout with a targeted message instead of a jit
            # shape error (the masked-gather head changed the contract)
            for v in (mlm_labels, mlm_weights):
                v.feed_hint = (
                    "build_pretrain(max_pred=%d) expects GATHERED "
                    "masked-position feeds: mlm_labels/mlm_weights are "
                    "[batch, %d] and mask_pos is required.  To keep the "
                    "legacy all-position [batch, seq_len] layout, build "
                    "with max_pred=0." % (max_pred, n_pred))
            # PER-SEQUENCE masked positions in [0, seq_len); weight 0
            # marks padding of the masked set.  The b*seq_len row offset
            # is added IN-GRAPH so the feed is shard-safe: under the
            # multi-process DP path each rank feeds only its local batch
            # shard, and host-side absolute indices would point into the
            # wrong rows of the assembled global batch
            mask_pos = fluid.layers.data("mask_pos", shape=[n_pred],
                                         dtype="int64")
        x = encoder(input_ids, token_type, mask_bias, cfg, seq_len)
        # MLM head: project back to vocab with the word embedding
        # transposed (weight tying, the standard BERT head).  With
        # max_pred the masked positions are gathered FIRST, so the
        # projection scores [B*max_pred, V] instead of [B*T, V].
        block = main.global_block()
        word_emb = block.var("bert.word_emb")
        if max_pred:
            # in-graph row offsets [B,1]: cumsum of a T-filled column
            # minus itself = b*T at row b (stays int64 throughout)
            rowT = fluid.layers.fill_constant_batch_size_like(
                mlm_weights, shape=[-1, 1], dtype="int64",
                value=float(seq_len))
            offs = fluid.layers.elementwise_sub(
                fluid.layers.cumsum(rowT, axis=0), rowT)
            abs_pos = fluid.layers.elementwise_add(mask_pos, offs)
            x = fluid.layers.reshape(x, shape=[-1, cfg.hidden])
            x = fluid.layers.gather(
                x, fluid.layers.reshape(abs_pos, shape=[-1]))
            labels2 = fluid.layers.reshape(mlm_labels, shape=[-1, 1])
            w_flat = fluid.layers.reshape(mlm_weights, shape=[-1])
        else:
            labels2 = fluid.layers.unsqueeze(mlm_labels, [2])
            w_flat = mlm_weights
        logits = fluid.layers.matmul(x, word_emb, transpose_y=True)
        loss_tok = fluid.layers.softmax_with_cross_entropy(logits, labels2)
        loss_tok = fluid.layers.squeeze(loss_tok, [1 if max_pred else 2])
        num = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(loss_tok, w_flat)
        )
        den = fluid.layers.reduce_sum(w_flat)
        loss = fluid.layers.elementwise_div(num, den)
        if train:
            opt = fluid.optimizer.Adam(learning_rate=lr)
            if amp:
                opt = fluid.contrib.mixed_precision.decorate(opt)
            opt.minimize(loss)
        elif amp:
            fluid.contrib.mixed_precision.rewrite_program_bf16(main)
    feeds = ["input_ids", "token_type_ids", "attn_mask_bias", "pos_ids",
             "mlm_labels", "mlm_weights"]
    if max_pred:
        feeds.append("mask_pos")
    return main, startup, feeds, loss


def make_fake_batch(batch, seq_len, cfg, rng, max_pred=None):
    """Fake MLM batch matching build_pretrain's feeds (same max_pred
    default — the two must agree on the masked-gather layout)."""
    import numpy as np

    if max_pred is None:
        max_pred = default_max_pred(seq_len)
    ids = rng.randint(10, cfg.vocab_size, (batch, seq_len)).astype("int64")
    types = np.zeros((batch, seq_len), "int64")
    mask = np.zeros((batch, 1, 1, seq_len), "float32")
    pos = np.tile(np.arange(seq_len, dtype="int64"), (batch, 1))
    out = {
        "input_ids": ids,
        "token_type_ids": types,
        "attn_mask_bias": mask,
        "pos_ids": pos,
    }
    if max_pred:
        n_real = min(max_pred, max(1, int(0.15 * seq_len)))
        mask_pos = np.zeros((batch, max_pred), "int64")
        labels = np.zeros((batch, max_pred), "int64")
        weights = np.zeros((batch, max_pred), "float32")
        for b in range(batch):
            picks = rng.permutation(seq_len)[:n_real]
            mask_pos[b, :n_real] = picks  # per-sequence; offset in-graph
            labels[b, :n_real] = ids[b, picks]
            weights[b, :n_real] = 1.0
        out["mask_pos"] = mask_pos
        out["mlm_labels"] = labels
        out["mlm_weights"] = weights
    else:
        out["mlm_labels"] = ids.copy()
        out["mlm_weights"] = (rng.rand(batch, seq_len) < 0.15).astype(
            "float32")
    return out
