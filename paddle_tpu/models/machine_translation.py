"""Seq2seq NMT with beam-search decoding (BASELINE config 4).

Reference: ``benchmark/fluid/models/machine_translation.py`` and the book
test ``tests/book/test_machine_translation.py`` — GRU encoder-decoder;
inference decodes with ``beam_search`` inside a ``While`` loop and
backtraces with ``beam_search_decode``.

TPU-static redesign: fixed source/target lengths (padded), dense [B, K]
beams, a hand-rolled GRU cell shared between the teacher-forced trainer
(StaticRNN → lax.scan) and the beam-search decoder (While → lax.while_loop)
via ParamAttr name sharing — the same weight-sharing mechanism the
reference uses between its train and infer programs.
"""

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr


def _shared(name):
    return ParamAttr(name=name)


def gru_cell(x, h, size, prefix):
    """Minimal GRU step on [N, E+H] inputs with nameable (shared) params."""
    gates = fluid.layers.fc(
        fluid.layers.concat([x, h], axis=1), size=2 * size, act="sigmoid",
        param_attr=_shared(prefix + "_gate_w"),
        bias_attr=_shared(prefix + "_gate_b"))
    r, u = fluid.layers.split(gates, 2, dim=1)
    c = fluid.layers.fc(
        fluid.layers.concat([x, fluid.layers.elementwise_mul(r, h)], axis=1),
        size=size, act="tanh",
        param_attr=_shared(prefix + "_cand_w"),
        bias_attr=_shared(prefix + "_cand_b"))
    one_minus_u = fluid.layers.scale(u, scale=-1.0, bias=1.0)
    return fluid.layers.elementwise_add(
        fluid.layers.elementwise_mul(u, h),
        fluid.layers.elementwise_mul(one_minus_u, c))


def encode(src, vocab_size, emb_dim, hidden_dim):
    """src [B, Ts] int64 → context [B, H] (last encoder state)."""
    src_emb = fluid.layers.embedding(
        src, size=[vocab_size, emb_dim], param_attr=_shared("src_emb"))
    proj = fluid.layers.fc(
        src_emb, size=3 * hidden_dim, num_flatten_dims=2,
        param_attr=_shared("enc_proj_w"), bias_attr=_shared("enc_proj_b"))
    enc = fluid.layers.dynamic_gru(
        proj, size=hidden_dim, param_attr=_shared("enc_gru_w"),
        bias_attr=_shared("enc_gru_b"))  # [B, Ts, H]
    Ts = src.shape[1]
    last = fluid.layers.slice(enc, axes=[1], starts=[Ts - 1], ends=[Ts])
    context = fluid.layers.reshape(last, shape=[-1, enc.shape[2]])
    h0 = fluid.layers.fc(
        context, size=hidden_dim, act="tanh",
        param_attr=_shared("dec_init_w"), bias_attr=_shared("dec_init_b"))
    return context, h0


def build_train(vocab_size, emb_dim=32, hidden_dim=64, src_len=8, tgt_len=8,
                lr=1e-3, batch_size=None):
    """Teacher-forced trainer.  Returns (main, startup, feeds, loss)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[src_len], dtype="int64")
        tgt_in = fluid.layers.data("tgt_in", shape=[tgt_len], dtype="int64")
        tgt_out = fluid.layers.data("tgt_out", shape=[tgt_len, 1],
                                    dtype="int64")
        context, h0 = encode(src, vocab_size, emb_dim, hidden_dim)

        tgt_emb = fluid.layers.embedding(
            tgt_in, size=[vocab_size, emb_dim], param_attr=_shared("tgt_emb"))
        # time-major for StaticRNN
        tgt_t = fluid.layers.transpose(tgt_emb, perm=[1, 0, 2])  # [T, B, E]

        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(tgt_t)              # [B, E]
            h = rnn.memory(init=h0)                  # [B, H]
            inp = fluid.layers.concat([x_t, context], axis=1)
            h_new = gru_cell(inp, h, hidden_dim, "dec_gru")
            rnn.update_memory(h, h_new)
            rnn.step_output(h_new)
        hiddens = rnn()                              # [T, B, H]

        logits = fluid.layers.fc(
            hiddens, size=vocab_size, num_flatten_dims=2,
            param_attr=_shared("out_w"), bias_attr=_shared("out_b"))
        labels_t = fluid.layers.transpose(tgt_out, perm=[1, 0, 2])  # [T,B,1]
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(logits, labels_t))
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, [src, tgt_in, tgt_out], loss


def build_train_dynamic(vocab_size, emb_dim=32, hidden_dim=64, src_len=8,
                        tgt_len=8, lr=1e-3):
    """Teacher-forced trainer whose decoder is a DynamicRNN over padded
    variable-length targets (the reference book model's decoder shape:
    ``python/paddle/fluid/tests/book/test_machine_translation.py`` uses
    DynamicRNN over ragged LoD targets; here targets are padded [B,T]
    with an explicit `tgt_lens` feed and the loss is length-masked).

    Returns (main, startup, feed names, loss)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[src_len], dtype="int64")
        tgt_in = fluid.layers.data("tgt_in", shape=[tgt_len], dtype="int64")
        tgt_out = fluid.layers.data("tgt_out", shape=[tgt_len, 1],
                                    dtype="int64")
        tgt_lens = fluid.layers.data("tgt_lens", shape=[], dtype="int64")
        context, h0 = encode(src, vocab_size, emb_dim, hidden_dim)

        tgt_emb = fluid.layers.embedding(
            tgt_in, size=[vocab_size, emb_dim], param_attr=_shared("tgt_emb"))

        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(tgt_emb, lengths=tgt_lens)  # [B, E]
            h = drnn.memory(init=h0)                          # [B, H]
            inp = fluid.layers.concat([x_t, context], axis=1)
            h_new = gru_cell(inp, h, hidden_dim, "dec_gru")
            drnn.update_memory(h, h_new)
            drnn.output(h_new)
        hiddens = drnn()                                      # [B, T, H]

        logits = fluid.layers.fc(
            hiddens, size=vocab_size, num_flatten_dims=2,
            param_attr=_shared("out_w"), bias_attr=_shared("out_b"))
        tok_loss = fluid.layers.softmax_with_cross_entropy(
            logits, tgt_out)                                  # [B, T, 1]
        mask = fluid.layers.cast(
            fluid.layers.sequence_mask(tgt_lens, maxlen=tgt_len), "float32")
        tok_loss = fluid.layers.elementwise_mul(
            fluid.layers.squeeze(tok_loss, axes=[2]), mask)
        loss = fluid.layers.elementwise_div(
            fluid.layers.reduce_sum(tok_loss),
            fluid.layers.reduce_sum(mask))
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, [src, tgt_in, tgt_out, tgt_lens], loss


def build_infer(vocab_size, emb_dim=32, hidden_dim=64, src_len=8,
                batch_size=4, beam_size=3, max_len=10, start_id=1, end_id=2):
    """Beam-search decoder sharing all parameters with build_train.

    Returns (main, startup, feeds, sentence_ids [B,K,max_len],
    sentence_scores [B,K]).
    """
    B, K = batch_size, beam_size
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[B, src_len], dtype="int64",
                                append_batch_size=False)
        context, h0 = encode(src, vocab_size, emb_dim, hidden_dim)

        # beams: all start on beam 0 (dense-beam first-step convention)
        pre_ids = fluid.layers.fill_constant([B, K], "int32",
                                             float(start_id))
        zero_col = fluid.layers.fill_constant([B, 1], "float32", 0.0)
        ninf_cols = fluid.layers.fill_constant([B, K - 1], "float32", -1e9)
        pre_scores = fluid.layers.concat([zero_col, ninf_cols], axis=1)

        # per-beam state/context: [B, H] → [B*K, H]
        def tile_beams(x):
            x3 = fluid.layers.unsqueeze(x, axes=[1])          # [B, 1, H]
            x3 = fluid.layers.expand(x3, expand_times=[1, K, 1])
            return fluid.layers.reshape(x3, shape=[B * K, -1])

        h = tile_beams(h0)
        ctx_tiled = tile_beams(context)

        i = fluid.layers.fill_constant([1], "int32", 0)
        # arrays need a pre-loop write so their buffers are loop-carried
        # (first in-loop write is overwritten at i=0 on the first iteration)
        zero_ids = fluid.layers.fill_constant([B, K], "int32", 0.0)
        zero_scores = fluid.layers.fill_constant([B, K], "float32", 0.0)
        ids_array = fluid.layers.array_write(zero_ids, i, capacity=max_len)
        scores_array = fluid.layers.array_write(zero_scores, i,
                                                capacity=max_len)
        parents_array = fluid.layers.array_write(zero_ids, i,
                                                 capacity=max_len)
        limit = fluid.layers.fill_constant([1], "int32", float(max_len))
        cond = fluid.layers.less_than(i, limit)
        # beam-offset rows for regrouping gathered parents: [B, K]
        row_offset = fluid.layers.reshape(
            fluid.layers.range(0, B * K, K, "int32"), shape=[B, 1])

        w = fluid.layers.While(cond)
        with w.block():
            flat_ids = fluid.layers.reshape(pre_ids, shape=[B * K])
            emb = fluid.layers.embedding(
                flat_ids, size=[vocab_size, emb_dim],
                param_attr=_shared("tgt_emb"))
            inp = fluid.layers.concat([emb, ctx_tiled], axis=1)
            h_new = gru_cell(inp, h, hidden_dim, "dec_gru")
            logits = fluid.layers.fc(
                h_new, size=vocab_size,
                param_attr=_shared("out_w"), bias_attr=_shared("out_b"))
            logp = fluid.layers.log_softmax(logits)
            logp3 = fluid.layers.reshape(logp, shape=[B, K, vocab_size])

            sel_ids, sel_scores, parent = fluid.layers.beam_search(
                pre_ids, pre_scores, None, logp3, beam_size=K,
                end_id=end_id, is_accumulated=False,
                return_parent_idx=True)

            # reorder beam states by parent: global row = b*K + parent
            global_parent = fluid.layers.reshape(
                fluid.layers.elementwise_add(parent, row_offset),
                shape=[B * K])
            h_reordered = fluid.layers.gather(h_new, global_parent)

            fluid.layers.array_write(sel_ids, i, ids_array)
            fluid.layers.array_write(sel_scores, i, scores_array)
            fluid.layers.array_write(parent, i, parents_array)

            fluid.layers.assign(sel_ids, output=pre_ids)
            fluid.layers.assign(sel_scores, output=pre_scores)
            fluid.layers.assign(h_reordered, output=h)
            fluid.layers.increment(i, value=1.0, in_place=True)

            # stop early once every beam has emitted end_id
            end_const = fluid.layers.fill_constant([B, K], "int32",
                                                   float(end_id))
            alive = fluid.layers.cast(
                fluid.layers.not_equal(sel_ids, end_const), "int32")
            any_alive = fluid.layers.greater_than(
                fluid.layers.reduce_sum(alive),
                fluid.layers.fill_constant([1], "int32", 0.0))
            in_range = fluid.layers.less_than(i, limit)
            fluid.layers.assign(
                fluid.layers.logical_and(any_alive, in_range), output=cond)

        sent_ids, sent_scores = fluid.layers.beam_search_decode(
            ids_array, scores_array, parents_array, beam_size=K,
            end_id=end_id)
    return main, startup, [src], sent_ids, sent_scores
