"""VGG-16 (reference: ``benchmark/fluid/models/vgg.py``)."""

import paddle_tpu as fluid


def vgg16(input, class_dim, is_test=False, data_format="NCHW"):
    def conv_block(inp, num_filter, groups):
        return fluid.nets.img_conv_group(
            input=inp, conv_num_filter=[num_filter] * groups,
            pool_size=2, pool_stride=2, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True,
            data_format=data_format,
        )

    c1 = conv_block(input, 64, 2)
    c2 = conv_block(c1, 128, 2)
    c3 = conv_block(c2, 256, 3)
    c4 = conv_block(c3, 512, 3)
    c5 = conv_block(c4, 512, 3)
    d1 = fluid.layers.dropout(c5, 0.5)
    fc1 = fluid.layers.fc(d1, size=512, act=None)
    bn = fluid.layers.batch_norm(fc1, act="relu", is_test=is_test,
                                 data_layout="NHWC")
    d2 = fluid.layers.dropout(bn, 0.5)
    fc2 = fluid.layers.fc(d2, size=512, act=None)
    return fluid.layers.fc(fc2, size=class_dim)


def build(dataset="cifar10", lr=1e-3, data_format="NCHW"):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        size = 32 if dataset == "cifar10" else 224
        shape = ([3, size, size] if data_format == "NCHW"
                 else [size, size, 3])
        class_dim = 10 if dataset == "cifar10" else 1000
        img = fluid.layers.data("img", shape=shape, dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        logits = vgg16(img, class_dim, data_format=data_format)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label)
        )
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, [img, label], loss, acc
