"""MNIST models (reference: ``benchmark/fluid/models/mnist.py`` and the book
test ``tests/book/test_recognize_digits.py`` — BASELINE config 1)."""

import paddle_tpu as fluid


def mlp(img, label, hidden_sizes=(200, 200)):
    h = img
    for size in hidden_sizes:
        h = fluid.layers.fc(h, size=size, act="relu")
    logits = fluid.layers.fc(h, size=10)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label)
    )
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return loss, acc, logits


def conv_net(img, label):
    """LeNet-style conv net (reference mnist.py cnn_model)."""
    conv1 = fluid.nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu",
    )
    conv2 = fluid.nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu",
    )
    logits = fluid.layers.fc(conv2, size=10)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label)
    )
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return loss, acc, logits


def build(use_conv=False, lr=1e-3):
    """Returns (main, startup, feeds, loss, acc)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if use_conv:
            img = fluid.layers.data("img", shape=[1, 28, 28], dtype="float32")
        else:
            img = fluid.layers.data("img", shape=[784], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        model = conv_net if use_conv else mlp
        loss, acc, _ = model(img, label)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, [img, label], loss, acc
