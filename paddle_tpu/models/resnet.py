"""ResNet for cifar/ImageNet (reference: ``benchmark/fluid/models/resnet.py``
— BASELINE config 2).

TPU notes: NCHW layout is the default for reference parity, but every
builder threads ``data_format`` and the bench exposes an NHWC arm —
channels-last is the TPU-native conv layout (the vector lane dimension),
and whether XLA's internal re-layout of NCHW costs real transposes is
an empirical question the hardware A/B answers (identical math either
way: conv filters stay OIHW, BN/bias are per-channel, the head pools to
[N,1,1,C] so the fc weight order matches — proven by
``tests/test_models.py`` layout-parity).  batch_norm is the framework's
batch_norm op whose running-stat updates ride the same jitted step."""

import paddle_tpu as fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_test=False, data_format="NCHW"):
    conv = fluid.layers.conv2d(
        input=input, num_filters=ch_out, filter_size=filter_size,
        stride=stride, padding=padding, bias_attr=False,
        data_format=data_format,
    )
    return fluid.layers.batch_norm(conv, act=act, is_test=is_test,
                                   data_layout=data_format)


def _shortcut(input, ch_in, ch_out, stride, is_test, data_format="NCHW"):
    if stride != 1 or ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_test=is_test, data_format=data_format)
    return input


def basicblock(input, ch_in, ch_out, stride, is_test, data_format="NCHW"):
    short = _shortcut(input, ch_in, ch_out, stride, is_test, data_format)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None,
                          is_test=is_test, data_format=data_format)
    return fluid.layers.elementwise_add(short, conv2, act="relu")


def bottleneck(input, ch_in, ch_out, stride, is_test, data_format="NCHW"):
    short = _shortcut(input, ch_in, ch_out * 4, stride, is_test,
                      data_format)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test,
                          data_format=data_format)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test, data_format=data_format)
    return fluid.layers.elementwise_add(short, conv3, act="relu")


def _layer_warp(block_func, input, ch_in, ch_out, count, stride, is_test,
                data_format="NCHW"):
    res = block_func(input, ch_in, ch_out, stride, is_test, data_format)
    for _ in range(1, count):
        res = block_func(res, ch_out, ch_out, 1, is_test, data_format)
    return res


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False,
                   data_format="NCHW"):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, 16, 3, 1, 1, is_test=is_test,
                          data_format=data_format)
    res1 = _layer_warp(basicblock, conv1, 16, 16, n, 1, is_test,
                       data_format)
    res2 = _layer_warp(basicblock, res1, 16, 32, n, 2, is_test,
                       data_format)
    res3 = _layer_warp(basicblock, res2, 32, 64, n, 2, is_test,
                       data_format)
    pool = fluid.layers.pool2d(res3, pool_size=8, pool_type="avg",
                               pool_stride=1, data_format=data_format)
    return fluid.layers.fc(pool, size=class_dim)


def _s2d_stem(input, is_test, data_format):
    """The 7x7/s2 stem recast via space-to-depth (block 2): the
    3-channel stride-2 conv under-fills the MXU's contraction lanes
    (7*7*3 = 147 sparse taps over a strided window); folding the
    stride into channels gives a dense 4x4/s1 conv over 12 channels on
    the 112x112 grid — the standard TPU ResNet stem recipe.  A free
    [64, 12, 4, 4] filter strictly contains the original [64, 3, 7, 7]
    class (pad 7x7 -> 8x8 with a zero row/col, space-to-depth the
    filter), so training from scratch is equivalent; checkpoints are
    not weight-compatible with the conv7 stem, hence opt-in
    (stem="s2d").  Output matches conv7 exactly in shape: [*, 64, 112,
    112] via asymmetric (1, 2) spatial padding."""
    if data_format == "NCHW":
        x = fluid.layers.space_to_depth(input, 2)      # [N,12,112,112]
        x = fluid.layers.pad(x, [0, 0, 0, 0, 1, 2, 1, 2])
    else:
        # channels-last: s2d expressed as reshape+transpose (the
        # space_to_depth op is NCHW by reference parity); XLA folds
        # this into the conv's input layout
        n, h, w, c = input.shape
        x = fluid.layers.reshape(
            input, [-1, h // 2, 2, w // 2, 2, c])
        x = fluid.layers.transpose(x, [0, 1, 3, 2, 4, 5])
        x = fluid.layers.reshape(x, [-1, h // 2, w // 2, 4 * c])
        x = fluid.layers.pad(x, [0, 0, 1, 2, 1, 2, 0, 0])
    return conv_bn_layer(x, 64, 4, 1, 0, is_test=is_test,
                         data_format=data_format)


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False,
                    data_format="NCHW", stem="conv7"):
    cfg = {
        18: ([2, 2, 2, 2], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]
    if stem == "s2d":
        conv1 = _s2d_stem(input, is_test, data_format)
    else:
        conv1 = conv_bn_layer(input, 64, 7, 2, 3, is_test=is_test,
                              data_format=data_format)
    pool1 = fluid.layers.pool2d(conv1, pool_size=3, pool_stride=2,
                                pool_padding=1, pool_type="max",
                                data_format=data_format)
    expansion = 4 if block_func is bottleneck else 1
    res = pool1
    ch_in = 64
    for i, count in enumerate(stages):
        ch_out = 64 * (2 ** i)
        stride = 1 if i == 0 else 2
        res = _layer_warp(block_func, res, ch_in, ch_out, count, stride,
                          is_test, data_format)
        ch_in = ch_out * expansion
    pool2 = fluid.layers.pool2d(res, pool_size=7, pool_type="avg",
                                global_pooling=True,
                                data_format=data_format)
    return fluid.layers.fc(pool2, size=class_dim)


def build(dataset="cifar10", depth=None, batch_lr=0.1, class_dim=None,
          is_test=False, amp=False, data_format="NCHW", stem="conv7"):
    """Returns (main, startup, feeds, loss, acc).  amp=True applies the
    bf16 AMP rewrite (fp32 master weights) like the BERT bench path.
    data_format="NHWC" builds the channels-last variant (the ``img``
    feed is then [H, W, C]).  stem="s2d" (imagenet only) uses the
    space-to-depth stem — see ``_s2d_stem``."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if dataset == "cifar10":
            shape = ([3, 32, 32] if data_format == "NCHW"
                     else [32, 32, 3])
            img = fluid.layers.data("img", shape=shape, dtype="float32")
            logits_fn = lambda im: resnet_cifar10(  # noqa: E731
                im, class_dim or 10, depth or 20, is_test, data_format
            )
        else:
            shape = ([3, 224, 224] if data_format == "NCHW"
                     else [224, 224, 3])
            img = fluid.layers.data("img", shape=shape, dtype="float32")
            logits_fn = lambda im: resnet_imagenet(  # noqa: E731
                im, class_dim or 1000, depth or 50, is_test, data_format,
                stem,
            )
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        logits = logits_fn(img)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label)
        )
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        opt = fluid.optimizer.Momentum(learning_rate=batch_lr, momentum=0.9,
                                       use_nesterov=True)
        if amp:
            opt = fluid.contrib.mixed_precision.decorate(opt)
        opt.minimize(loss)
    return main, startup, [img, label], loss, acc
