"""Stacked dynamic-LSTM sentiment model (reference:
``benchmark/fluid/models/stacked_dynamic_lstm.py`` — embedding → fc →
stacked LSTM layers → max pools → fc head; ragged LoD batches there,
padded [B, T] + lengths here)."""

import paddle_tpu as fluid


def build(vocab_size=5149, seq_len=80, emb_dim=512, hidden_dim=512,
          stacked_num=3, class_dim=2, lr=1e-3):
    """Returns (main, startup, feed names, loss, acc)."""
    assert stacked_num % 2 == 1, "stacked_num must be odd (reference)"
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = fluid.layers.data("words", shape=[seq_len], dtype="int64")
        lens = fluid.layers.data("lens", shape=[], dtype="int64")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(
            words, size=[vocab_size, emb_dim],
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.Uniform(-0.1, 0.1)))
        # dynamic_lstm takes pre-projected [B, T, 4*hidden] gates
        # (size = 4*hidden, the reference convention)
        fc1 = fluid.layers.fc(emb, size=hidden_dim * 4,
                              num_flatten_dims=2, act="tanh")
        lstm1, _ = fluid.layers.dynamic_lstm(
            fc1, size=hidden_dim * 4, seq_len=lens)
        inputs = [fc1, lstm1]
        for i in range(2, stacked_num + 1):
            fc = fluid.layers.fc(
                fluid.layers.concat(inputs, axis=2),
                size=hidden_dim * 4, num_flatten_dims=2, act="tanh")
            lstm, _ = fluid.layers.dynamic_lstm(
                fc, size=hidden_dim * 4, is_reverse=(i % 2) == 0,
                seq_len=lens)
            inputs = [fc, lstm]
        # sequence max-pools over the time dim, masked by length
        mask = fluid.layers.cast(
            fluid.layers.sequence_mask(lens, maxlen=seq_len), "float32")
        neg = fluid.layers.scale(
            fluid.layers.elementwise_sub(
                fluid.layers.unsqueeze(mask, [2]),
                fluid.layers.fill_constant([1], "float32", 1.0)),
            scale=1e9)

        def masked_max(x):
            return fluid.layers.reduce_max(
                fluid.layers.elementwise_add(x, neg), dim=[1])

        fc_last = masked_max(inputs[0])
        lstm_last = masked_max(inputs[1])
        logits = fluid.layers.fc(
            fluid.layers.concat([fc_last, lstm_last], axis=1),
            size=class_dim)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, ["words", "lens", "label"], loss, acc
