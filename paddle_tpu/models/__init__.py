"""Model zoo built on the layers DSL (reference:
``benchmark/fluid/models/`` {mnist,resnet,vgg,...}.py and the book tests
``python/paddle/fluid/tests/book/``)."""

from . import mnist
from . import resnet
from . import bert
from . import vgg
from . import ctr
from . import machine_translation
from . import se_resnext
from . import stacked_dynamic_lstm

__all__ = ["mnist", "resnet", "bert", "vgg", "ctr",
           "machine_translation", "se_resnext", "stacked_dynamic_lstm"]
