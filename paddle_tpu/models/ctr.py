"""CTR models: Wide&Deep and DeepFM (BASELINE config 5; reference
analogues: ``benchmark/fluid`` ctr workloads, ``dist_ctr.py`` test model).

TPU-native sparse path: each categorical slot is a padded [B, L] int64
tensor (0 = padding id); embeddings are `lookup_table` ops whose grads are
XLA scatter-adds (the SelectedRows sparse-grad role), and huge tables can
be sharded over a mesh axis via is_distributed=True (row sharding — the
distributed-lookup-table role, ``parameter_prefetch.cc``)."""

import paddle_tpu as fluid


def _host_slot_embed_sum(slot, vocab, dim, name, lr=0.01):
    """Host-resident variant of a slot embedding (bigger-than-HBM tables:
    ``paddle_tpu.host_table``): masked sum so padding id 0 contributes
    nothing, like the device path's padding_idx=0."""
    slab = fluid.layers.host_embedding(slot, size=[vocab, dim], name=name,
                                       lr=lr)
    zero = fluid.layers.fill_constant([1], "int64", 0)
    mask = fluid.layers.cast(fluid.layers.not_equal(slot, zero), "float32")
    masked = fluid.layers.elementwise_mul(
        slab, fluid.layers.unsqueeze(mask, [2]))
    return fluid.layers.reduce_sum(masked, dim=1)  # [B, dim]


def _slot_embed_sum(slot, vocab, dim, name, is_sparse=True,
                    is_distributed=False):
    emb = fluid.layers.embedding(
        slot, size=[vocab, dim], is_sparse=is_sparse,
        is_distributed=is_distributed, padding_idx=0,
        param_attr=fluid.ParamAttr(
            name=name,
            initializer=fluid.initializer.Uniform(-0.01, 0.01),
        ),
    )  # [B, L, dim]
    return fluid.layers.reduce_sum(emb, dim=1)  # [B, dim]


def wide_deep(slots, dense, label, vocab=100000, embed_dim=16,
              hidden=(400, 400, 400), is_distributed=False, is_sparse=True):
    """Wide (linear over slots) + Deep (MLP over embeddings + dense)."""
    # deep part
    deep_in = [
        _slot_embed_sum(s, vocab, embed_dim, "deep_emb_%d" % i,
                        is_sparse=is_sparse, is_distributed=is_distributed)
        for i, s in enumerate(slots)
    ]
    if dense is not None:
        deep_in.append(dense)
    x = fluid.layers.concat(deep_in, axis=1)
    for i, h in enumerate(hidden):
        x = fluid.layers.fc(x, size=h, act="relu")
    deep_logit = fluid.layers.fc(x, size=1)
    # wide part: per-slot scalar embeddings (linear terms)
    wide_terms = [
        _slot_embed_sum(s, vocab, 1, "wide_emb_%d" % i,
                        is_sparse=is_sparse, is_distributed=is_distributed)
        for i, s in enumerate(slots)
    ]
    wide_logit = fluid.layers.sums(wide_terms)
    if dense is not None:
        wide_logit = fluid.layers.elementwise_add(
            wide_logit, fluid.layers.fc(dense, size=1)
        )
    logit = fluid.layers.elementwise_add(deep_logit, wide_logit)
    loss = fluid.layers.mean(
        fluid.layers.sigmoid_cross_entropy_with_logits(
            logit, fluid.layers.cast(label, "float32")
        )
    )
    from ..layers import ops as _ops

    prob = _ops.sigmoid(logit)
    return loss, prob


def deepfm(slots, label, vocab=100000, embed_dim=16, hidden=(400, 400),
           is_distributed=False, use_host_table=False, host_lr=0.01):
    """DeepFM: first-order linear + second-order FM interactions + deep
    MLP, all sharing slot embeddings.  ``use_host_table`` keeps the
    tables in host RAM (the >HBM CTR deployment; the tables then train
    with their own sparse-SGD lr, like the reference pserver's separate
    optimizer blocks).  When the tables FIT device memory, leave
    ``use_host_table=False``: the lookups are then in-graph
    ``lookup_table`` ops the fusion pipeline dispatches to the Pallas
    row-DMA gather kernel (``fused_embedding_gather``, lane-aligned
    dims), and ``is_distributed=True`` row-shards each table over the
    mesh — the device-side migration of the reference's distributed
    lookup_table (see MIGRATION.md)."""
    embs = []     # [B, dim] per slot (slot-summed)
    firsts = []   # [B, 1] per slot
    for i, s in enumerate(slots):
        if use_host_table:
            embs.append(_host_slot_embed_sum(
                s, vocab, embed_dim, "fm_emb_%d" % i, lr=host_lr))
            firsts.append(_host_slot_embed_sum(
                s, vocab, 1, "fm_first_%d" % i, lr=host_lr))
            continue
        e = fluid.layers.embedding(
            s, size=[vocab, embed_dim], is_sparse=True, padding_idx=0,
            is_distributed=is_distributed,
            param_attr=fluid.ParamAttr(
                name="fm_emb_%d" % i,
                initializer=fluid.initializer.Uniform(-0.01, 0.01),
            ),
        )
        embs.append(fluid.layers.reduce_sum(e, dim=1))  # [B, dim]
        firsts.append(
            _slot_embed_sum(s, vocab, 1, "fm_first_%d" % i,
                            is_distributed=is_distributed)
        )
    first_order = fluid.layers.sums(firsts)  # [B,1]
    # FM second order: 0.5 * ((sum v)^2 - sum v^2), summed over dim
    stacked = fluid.layers.stack(embs, axis=1)  # [B, S, dim]
    sum_v = fluid.layers.reduce_sum(stacked, dim=1)          # [B, dim]
    sum_sq = fluid.layers.reduce_sum(
        fluid.layers.elementwise_mul(stacked, stacked), dim=1
    )
    second = fluid.layers.reduce_sum(
        fluid.layers.elementwise_sub(
            fluid.layers.elementwise_mul(sum_v, sum_v), sum_sq
        ),
        dim=1, keep_dim=True,
    )
    second = fluid.layers.scale(second, scale=0.5)
    # deep
    x = fluid.layers.concat(embs, axis=1)
    for h in hidden:
        x = fluid.layers.fc(x, size=h, act="relu")
    deep_logit = fluid.layers.fc(x, size=1)
    logit = fluid.layers.sums([first_order, second, deep_logit])
    loss = fluid.layers.mean(
        fluid.layers.sigmoid_cross_entropy_with_logits(
            logit, fluid.layers.cast(label, "float32")
        )
    )
    from ..layers import ops as _ops

    return loss, _ops.sigmoid(logit)


def build(model="wide_deep", num_slots=8, slot_len=4, dense_dim=13,
          vocab=100000, lr=1e-3, is_distributed=False,
          use_host_table=False, host_lr=0.01, embed_dim=16):
    """Returns (main, startup, feed_vars, loss, prob)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        slots = [
            fluid.layers.data("slot_%d" % i, shape=[slot_len],
                              dtype="int64")
            for i in range(num_slots)
        ]
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        feeds = list(slots) + [label]
        if model == "wide_deep":
            if use_host_table:
                raise ValueError(
                    "use_host_table is implemented for model='deepfm' "
                    "only; wide_deep still uses device tables")
            dense = fluid.layers.data("dense", shape=[dense_dim],
                                      dtype="float32")
            feeds.append(dense)
            loss, prob = wide_deep(slots, dense, label, vocab,
                                   is_distributed=is_distributed)
        else:
            loss, prob = deepfm(slots, label, vocab,
                                embed_dim=embed_dim,
                                is_distributed=is_distributed,
                                use_host_table=use_host_table,
                                host_lr=host_lr)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, feeds, loss, prob


def run_deepfm_device_table_steps(steps=5, num_slots=4, slot_len=3,
                                  vocab=100000, batch=16, embed_dim=128,
                                  seed=8):
    """Device-table twin of :func:`run_deepfm_host_table_steps`: the
    same DeepFM with the embedding tables as in-graph device parameters
    (lane-aligned dim so the fusion pipeline dispatches the lookups to
    the Pallas gather kernel).  Returns (per-step losses, FusionReport)
    so tests/benches can assert the ``fused_embedding_gather`` sites
    actually fired on the path that ran."""
    import numpy as np

    from ..executor import Scope, scope_guard
    from ..static_analysis import fusion

    fluid.unique_name.switch()
    main, startup, feeds, loss, prob = build(
        model="deepfm", num_slots=num_slots, slot_len=slot_len,
        vocab=vocab, embed_dim=embed_dim, use_host_table=False)
    _, report = fusion.resolve_fused_program(main, targets=[loss.name])
    rng = np.random.RandomState(seed)
    feed = {"slot_%d" % i:
            rng.randint(0, vocab, (batch, slot_len)).astype("int64")
            for i in range(num_slots)}
    feed["label"] = rng.randint(0, 2, (batch, 1)).astype("int64")
    exe = fluid.Executor(fluid.TPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    return losses, report


def run_deepfm_host_table_steps(steps=5, data_parallel=False, places=None,
                                num_slots=4, slot_len=3, vocab=100000,
                                batch=16, host_lr=0.05, seed=8):
    """Shared smoke recipe (used by tests AND the driver dryrun): build
    DeepFM with host-resident tables, train ``steps`` on a fixed batch,
    return the per-step losses.  ``data_parallel`` routes through
    CompiledProgram.with_data_parallel over ``places`` (None = all)."""
    import numpy as np

    from .. import host_table
    from ..executor import Scope, scope_guard

    host_table.reset_tables()
    fluid.unique_name.switch()
    main, startup, feeds, loss, prob = build(
        model="deepfm", num_slots=num_slots, slot_len=slot_len,
        vocab=vocab, use_host_table=True, host_lr=host_lr)
    rng = np.random.RandomState(seed)
    feed = {"slot_%d" % i:
            rng.randint(0, vocab, (batch, slot_len)).astype("int64")
            for i in range(num_slots)}
    feed["label"] = rng.randint(0, 2, (batch, 1)).astype("int64")
    exe = fluid.Executor(fluid.TPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        target = main
        if data_parallel:
            target = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, places=places)
        for _ in range(steps):
            (lv,) = exe.run(target, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
        for i in range(num_slots):
            host_table.get_table("fm_emb_%d" % i).join()
            host_table.get_table("fm_first_%d" % i).join()
    return losses
