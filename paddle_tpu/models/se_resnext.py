"""SE-ResNeXt (reference: ``benchmark/fluid/models/se_resnext.py`` —
grouped bottleneck convs with squeeze-and-excitation gates)."""

import paddle_tpu as fluid


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False):
    conv = fluid.layers.conv2d(
        input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False)
    return fluid.layers.batch_norm(conv, act=act, is_test=is_test)


def squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = fluid.layers.pool2d(input, pool_type="avg", global_pooling=True)
    squeeze = fluid.layers.fc(pool, size=num_channels // reduction_ratio,
                              act="relu")
    excitation = fluid.layers.fc(squeeze, size=num_channels,
                                 act="sigmoid")
    # gate channels: [B, C] → [B, C, 1, 1]
    gate = fluid.layers.unsqueeze(
        fluid.layers.unsqueeze(excitation, [2]), [3])
    return fluid.layers.elementwise_mul(input, gate)


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu", is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_test=is_test)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    ch_in = input.shape[1]
    if ch_in == num_filters * 2 and stride == 1:
        short = input
    else:
        short = conv_bn_layer(input, num_filters * 2, 1, stride=stride,
                              is_test=is_test)
    return fluid.layers.elementwise_add(short, scale, act="relu")


def se_resnext(input, class_dim=10, cardinality=8, reduction_ratio=16,
               depth=(1, 1, 1), num_filters=(32, 64, 128), is_test=False):
    """Compact SE-ResNeXt (the benchmark's 50/152 shape with configurable
    depth so the CPU tests stay fast)."""
    conv = conv_bn_layer(input, 32, 3, stride=1, act="relu",
                         is_test=is_test)
    for block, nf in zip(depth, num_filters):
        for i in range(block):
            conv = bottleneck_block(
                conv, nf, stride=2 if i == 0 else 1,
                cardinality=cardinality,
                reduction_ratio=reduction_ratio, is_test=is_test)
    pool = fluid.layers.pool2d(conv, pool_type="avg", global_pooling=True)
    drop = pool if is_test else fluid.layers.dropout(
        pool, 0.2, dropout_implementation="upscale_in_train")
    return fluid.layers.fc(drop, size=class_dim)


def build(image_shape=(3, 32, 32), class_dim=10, lr=1e-2, is_test=False,
          **net_kwargs):
    """Returns (main, startup, feeds, loss, acc)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=list(image_shape),
                                dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        logits = se_resnext(img, class_dim, is_test=is_test, **net_kwargs)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        if not is_test:
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
            opt.minimize(loss)
    return main, startup, [img, label], loss, acc
