"""RecordIO conversion helpers (reference:
``python/paddle/fluid/recordio_writer.py`` —
``convert_reader_to_recordio_file`` serializes feeder-built batches into a
recordio file consumed by reader ops).

Serialization here is npz-per-record (a record holds one sample: a tuple of
arrays) over the native chunked writer (paddle_tpu/native/src/recordio.cc).
"""

import io

import numpy as np

from . import native

__all__ = [
    "convert_reader_to_recordio_file",
    "convert_reader_to_recordio_files",
    "recordio_reader",
]


def _pack(sample):
    buf = io.BytesIO()
    arrays = {("f%d" % i): np.asarray(a) for i, a in enumerate(sample)}
    np.savez(buf, **arrays)
    return buf.getvalue()


def _unpack(record):
    with np.load(io.BytesIO(record)) as z:
        return tuple(z["f%d" % i] for i in range(len(z.files)))


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None,
                                    compressor=None, max_num_records=1000):
    """Writes every sample from ``reader_creator()`` into ``filename``.
    Returns the number of records written."""
    count = 0
    with native.RecordIOWriter(filename,
                               max_chunk_records=max_num_records) as w:
        for sample in reader_creator():
            if not isinstance(sample, (tuple, list)):
                sample = (sample,)
            w.write(_pack(sample))
            count += 1
    return count


def recordio_reader(filename):
    """Reader creator yielding the samples stored in ``filename``."""

    def reader():
        with native.RecordIOScanner(filename) as s:
            for record in s:
                yield _unpack(record)

    return reader


def convert_reader_to_recordio_files(
        filename, batch_per_file, reader_creator, feeder=None,
        compressor=None, max_num_records=1000):
    """Multi-file variant (reference recordio_writer.py): split the
    stream into files of ``batch_per_file`` records named
    ``filename-00000`` etc.  Returns the list of paths written."""
    paths = []
    buf = []

    def flush():
        if not buf:
            return
        path = "%s-%05d" % (filename, len(paths))
        with native.RecordIOWriter(
                path, max_chunk_records=max_num_records) as w:
            for s in buf:
                w.write(_pack(s))
        paths.append(path)
        buf.clear()

    for sample in reader_creator():
        if not isinstance(sample, (tuple, list)):
            sample = (sample,)
        buf.append(sample)
        if len(buf) >= batch_per_file:
            flush()
    flush()
    return paths
