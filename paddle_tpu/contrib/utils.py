"""HDFS helpers (reference: ``python/paddle/fluid/contrib/utils/
hdfs_utils.py`` — HDFSClient shells out to the ``hadoop fs`` CLI;
multi_download / multi_upload fan the transfers over a process pool).

Same design here: a thin subprocess wrapper over ``$HADOOP_HOME/bin/
hadoop fs`` with the reference's method surface.  No hadoop binary on
the machine → a targeted RuntimeError at call time (not import time)."""

import os
import subprocess

__all__ = ["HDFSClient", "multi_download", "multi_upload"]


class HDFSClient:
    def __init__(self, hadoop_home, configs):
        self.hadoop_home = hadoop_home
        self.configs = dict(configs or {})

    def _cmd(self, *args):
        binary = os.path.join(self.hadoop_home, "bin", "hadoop")
        if not os.path.exists(binary):
            raise RuntimeError(
                "hadoop CLI not found at %s — HDFSClient drives the "
                "'hadoop fs' commands like the reference hdfs_utils.py"
                % binary)
        flags = []
        for k, v in self.configs.items():
            flags += ["-D", "%s=%s" % (k, v)]
        p = subprocess.run([binary, "fs"] + flags + list(args),
                           capture_output=True, text=True, timeout=600)
        return p.returncode, p.stdout, p.stderr

    def is_exist(self, hdfs_path=None):
        rc, _, _ = self._cmd("-test", "-e", hdfs_path)
        return rc == 0

    def is_dir(self, hdfs_path=None):
        rc, _, _ = self._cmd("-test", "-d", hdfs_path)
        return rc == 0

    def is_file(self, hdfs_path=None):
        return self.is_exist(hdfs_path) and not self.is_dir(hdfs_path)

    def delete(self, hdfs_path):
        rc, _, _ = self._cmd("-rm", "-r", "-skipTrash", hdfs_path)
        return rc == 0

    def rename(self, hdfs_src_path, hdfs_dst_path, overwrite=False):
        if overwrite and self.is_exist(hdfs_dst_path):
            self.delete(hdfs_dst_path)
        rc, _, _ = self._cmd("-mv", hdfs_src_path, hdfs_dst_path)
        return rc == 0

    def makedirs(self, hdfs_path):
        rc, _, _ = self._cmd("-mkdir", "-p", hdfs_path)
        return rc == 0

    def ls(self, hdfs_path):
        rc, out, _ = self._cmd("-ls", hdfs_path)
        if rc != 0:
            return []
        return [ln.split()[-1] for ln in out.splitlines()
                if ln and not ln.startswith("Found")]

    def lsr(self, hdfs_path, only_file=True, sort=True):
        rc, out, _ = self._cmd("-ls", "-R", hdfs_path)
        if rc != 0:
            return []
        items = []
        for ln in out.splitlines():
            parts = ln.split()
            if len(parts) < 8:
                continue
            if only_file and parts[0].startswith("d"):
                continue
            items.append(parts[-1])
        return sorted(items) if sort else items

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        args = ["-put"] + (["-f"] if overwrite else []) + [local_path,
                                                           hdfs_path]
        for _ in range(max(1, retry_times)):
            rc, _, _ = self._cmd(*args)
            if rc == 0:
                return True
        return False

    def download(self, hdfs_path, local_path, overwrite=False,
                 unzip=False):
        if overwrite and os.path.exists(local_path):
            if os.path.isdir(local_path):
                import shutil

                shutil.rmtree(local_path)
            else:
                os.remove(local_path)
        rc, _, _ = self._cmd("-get", hdfs_path, local_path)
        return rc == 0

    def touch(self, hdfs_path):
        rc, _, _ = self._cmd("-touchz", hdfs_path)
        return rc == 0

    @staticmethod
    def make_local_dirs(local_path):
        os.makedirs(local_path, exist_ok=True)


def multi_download(client, hdfs_path, local_path, trainer_id, trainers,
                   multi_processes=5):
    """reference hdfs_utils.multi_download: each trainer downloads its
    round-robin share of the files under hdfs_path."""
    files = client.lsr(hdfs_path)
    mine = [f for i, f in enumerate(files) if i % trainers == trainer_id]
    os.makedirs(local_path, exist_ok=True)
    out = []
    prefix = hdfs_path.rstrip("/") + "/"
    for f in mine:
        # keep the relative structure: same-named files in different
        # subdirectories must not overwrite each other
        rel = f[len(prefix):] if f.startswith(prefix) else \
            os.path.basename(f)
        dst = os.path.join(local_path, rel)
        os.makedirs(os.path.dirname(dst) or local_path, exist_ok=True)
        if client.download(f, dst, overwrite=True):
            out.append(dst)
    return out


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False, sync=True):
    """reference hdfs_utils.multi_upload: upload every file under
    local_path."""
    client.makedirs(hdfs_path)
    out = []
    for root, _, names in os.walk(local_path):
        for n in names:
            src = os.path.join(root, n)
            rel = os.path.relpath(src, local_path)
            dst = os.path.join(hdfs_path, rel)
            client.makedirs(os.path.dirname(dst))
            if client.upload(dst, src, overwrite=overwrite):
                out.append(dst)
    return out
