"""Decoupled weight decay (reference:
``python/paddle/fluid/contrib/extend_optimizer/
extend_optimizer_with_weight_decay.py``): mixes a decay step into any
optimizer — params are scaled down by ``coeff`` via in-graph ops
appended BEFORE the optimizer update (the AdamW-style decoupling: decay
is not part of the gradient, so adaptive scaling never touches it).

TPU note: the decay ops (scale → sub → assign) land in the same jitted
step as the update, so XLA fuses them into the (fused-)Adam stream —
the decoupling costs no extra HBM pass."""

from ...framework import Variable

__all__ = ["extend_with_decoupled_weight_decay"]


class DecoupledWeightDecay:
    def __init__(self, coeff=0.0, apply_decay_param_fun=None, **kwargs):
        if not isinstance(coeff, (float, int)) and \
                not isinstance(coeff, Variable):
            raise TypeError("coeff should be float or Variable")
        self._coeff = coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._decayed_params = set()
        super().__init__(**kwargs)

    def _append_decay_ops(self, params_grads):
        from ... import layers

        if isinstance(self._coeff, (float, int)) and \
                float(self._coeff) == 0.0:
            return
        for param, grad in params_grads:
            if grad is None:
                continue
            if self._apply_decay_param_fun is not None and \
                    not self._apply_decay_param_fun(param.name):
                continue
            if param.name in self._decayed_params:
                raise RuntimeError(
                    "param %r already decayed by this optimizer"
                    % param.name)
            self._decayed_params.add(param.name)
            if isinstance(self._coeff, Variable):
                scaled = layers.elementwise_mul(param, self._coeff)
            else:
                scaled = layers.scale(param, scale=float(self._coeff))
            updated = layers.elementwise_sub(param, scaled)
            layers.assign(updated, output=param)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        from ...clip import per_call_gradient_clip

        params_grads = self.backward(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        # decay ops precede the optimizer ops in program order, so the
        # update reads the already-decayed param (reference order)
        self._append_decay_ops(params_grads)
        with per_call_gradient_clip(loss.block.program, grad_clip):
            optimize_ops = self.apply_optimize(
                loss, startup_program, params_grads)
        return optimize_ops, params_grads

    def __str__(self):
        return "Weight Decay, params: %s" % ",".join(
            sorted(self._decayed_params))


def extend_with_decoupled_weight_decay(base_optimizer):
    """Returns a subclass of ``base_optimizer`` whose minimize applies
    decoupled weight decay (reference :102).  Usage::

        AdamW = extend_with_decoupled_weight_decay(fluid.optimizer.Adam)
        AdamW(weight_decay=0.01, learning_rate=1e-3).minimize(loss)
    """
    from ...optimizer import Optimizer

    if not issubclass(base_optimizer, Optimizer):
        raise TypeError("base_optimizer must be an Optimizer subclass")

    class OptimizerWithDecoupledWeightDecay(DecoupledWeightDecay,
                                            base_optimizer):
        def __init__(self, weight_decay, apply_decay_param_fun=None,
                     **kwargs):
            super().__init__(weight_decay, apply_decay_param_fun,
                             **kwargs)

    return OptimizerWithDecoupledWeightDecay
