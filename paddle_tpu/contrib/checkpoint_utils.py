"""Incremental-training checkpoint helpers (reference:
``contrib/utils/lookup_table_utils.py`` — reload persistables around a
distributed lookup table for incremental/inference runs) and the
dense→sparse program converter (``contrib/sparsity`` era API)."""

__all__ = ["load_persistables_for_increment",
           "load_persistables_for_inference",
           "convert_dist_to_sparse_program"]


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var=None,
                                    lookup_table_var_path=None):
    """Reload a checkpoint to continue training.  The reference
    re-assembles pserver-sharded lookup tables; here sharded tables
    reshard on load and host tables load via the shared shard layout, so
    the plain load covers both."""
    from ..io import load_persistables

    return load_persistables(executor, dirname, main_program=program)


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name=None):
    """Reload a checkpoint for inference (reference pulls the remote
    table to the local program; subsumed as above)."""
    from ..io import load_persistables

    return load_persistables(executor, dirname, main_program=program)


def convert_dist_to_sparse_program(program):
    """reference contrib.convert_dist_to_sparse_program: rewrite dense
    lookup tables to the sparse-update form.  Sparse embedding grads are
    native here (lookup_table emits scatter-add grads; SelectedRows
    role), so the program is already in the converted form."""
    return program
