"""Low-precision op lists (reference:
``python/paddle/fluid/contrib/mixed_precision/fp16_lists.py``).

TPU note: the low precision is **bfloat16**, not float16 — same exponent
range as fp32, so no loss scaling is required and the dynamic-loss-scaling
machinery of the reference degenerates to a no-op."""

# matmul-class ops: run in bf16 on the MXU (fp32 accumulation is set via
# preferred_element_type in the op lowerings)
white_list = {
    "mul",
    "matmul",
    "conv2d",
    "depthwise_conv2d",
    "conv3d",
    "conv2d_transpose",
}

# numerically sensitive ops: keep fp32 inputs
black_list = {
    "softmax_with_cross_entropy",
    "cross_entropy",
    "softmax",
    "log_softmax",
    "mean",
    "reduce_mean",
    "reduce_sum",
    "layer_norm",
    "batch_norm",
    "exp",
    "log",
    "squared_l2_norm",
}

# everything else follows its inputs
gray_list = set()


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
