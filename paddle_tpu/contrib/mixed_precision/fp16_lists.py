"""Low-precision op lists (reference:
``python/paddle/fluid/contrib/mixed_precision/fp16_lists.py``).

TPU note: the low precision is **bfloat16**, not float16 — same exponent
range as fp32, so no loss scaling is required and the dynamic-loss-scaling
machinery of the reference degenerates to a no-op."""

# bf16 compute set.  TPU-native AMP runs the whole compute body in bf16 —
# matmuls on the MXU (fp32 accumulation via preferred_element_type in the
# lowerings) AND the elementwise/norm/shape glue between them.  Keeping the
# glue f32 (the reference's GPU-era policy) forces a bf16↔f32 ping-pong
# around every matmul that doubles HBM traffic and measurably loses MFU;
# numerically-sensitive internals (layer_norm stats, softmax exp) upcast to
# f32 inside their own lowerings, so whitelisting them is safe.
white_list = {
    # matmul-class
    "mul",
    "matmul",
    "conv2d",
    "depthwise_conv2d",
    "conv3d",
    "conv2d_transpose",
    "fused_dropout_add_ln",
    "fused_multihead_attention",
    # elementwise / activation glue
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "scale",
    "sum",
    "relu",
    "gelu",
    "tanh",
    "sigmoid",
    "swish",
    "leaky_relu",
    "dropout",
    # shape glue (cast-free but keeps dtype propagation consistent)
    "reshape",
    "reshape2",
    "transpose",
    "transpose2",
    "concat",
    "split",
    "stack",
    "slice",
    "squeeze",
    "squeeze2",
    "unsqueeze",
    "unsqueeze2",
    "expand",
    "pad",
    # normalization / attention softmax / fused loss (f32 internals in
    # the lowerings)
    "layer_norm",
    "softmax",
    "softmax_with_cross_entropy",
}

# numerically sensitive ops: keep fp32 inputs (loss path + norms whose
# lowerings lack f32 internals)
black_list = {
    "cross_entropy",
    "log_softmax",
    "mean",
    "reduce_mean",
    "reduce_sum",
    "batch_norm",
    "exp",
    "log",
    "squared_l2_norm",
}

# everything else follows its inputs
gray_list = set()


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
