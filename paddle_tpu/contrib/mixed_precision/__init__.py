from .decorator import decorate, OptimizerWithMixedPrecision
from . import fp16_lists

__all__ = ["decorate", "OptimizerWithMixedPrecision", "fp16_lists"]
