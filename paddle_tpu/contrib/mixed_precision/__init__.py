from .decorator import decorate, OptimizerWithMixedPrecision, rewrite_program_bf16
from . import fp16_lists
from .fp16_lists import AutoMixedPrecisionLists

__all__ = ["decorate", "OptimizerWithMixedPrecision", "rewrite_program_bf16",
           "fp16_lists", "AutoMixedPrecisionLists"]
