"""AMP optimizer decorator (reference:
``python/paddle/fluid/contrib/mixed_precision/decorator.py:27``
OptimizerWithMixedPrecision: fp16 casts by white/black list, dynamic loss
scaling, fp32 master weights).

TPU-native: bf16 instead of fp16.  The program rewrite inserts `cast` ops in
front of white-listed (matmul-class) ops, so the MXU consumes bf16 while
params remain fp32 masters; the cast op's vjp casts grads back to fp32, which
IS the master-weight scheme.  bf16's fp32-equal exponent range makes loss
scaling unnecessary — the loss-scaling knobs are accepted and ignored."""

from ... import unique_name
from ...framework import default_main_program
from .fp16_lists import AutoMixedPrecisionLists

__all__ = ["decorate", "OptimizerWithMixedPrecision", "rewrite_program_bf16"]


def rewrite_program_bf16(program, amp_lists=None):
    """Insert bf16 casts before white-listed ops and fp32 casts before
    black-listed ops (reference fp16_utils.py rewrite_program)."""
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    block = program.global_block()
    cast_cache = {}  # (var, dtype) -> cast var name
    new_ops = []

    def cast_input(op, target_dtype, from_dtypes):
        for slot, names in op.inputs.items():
            new_names = []
            for n in names:
                var = block._find_var_recursive(n)
                if var is None or var.dtype not in from_dtypes:
                    new_names.append(n)
                    continue
                key = (n, target_dtype)
                if key not in cast_cache:
                    cast_name = unique_name.generate(n + ".cast_" + target_dtype)
                    cv = block.create_var(
                        name=cast_name, shape=var.shape, dtype=target_dtype,
                        persistable=False, stop_gradient=var.stop_gradient,
                    )
                    from ...framework import Operator

                    cast_op = Operator(
                        block, "cast",
                        {"X": [n]}, {"Out": [cast_name]},
                        {"in_dtype": var.dtype, "out_dtype": target_dtype},
                    )
                    new_ops.append(cast_op)
                    cast_cache[key] = cast_name
                new_names.append(cast_cache[key])
            op.inputs[slot] = new_names

    for op in block.ops:
        if op.type in amp_lists.white_list:
            cast_input(op, "bfloat16", ("float32",))
            # downstream vars produced by this op are bf16 at runtime
            for name in op.output_arg_names:
                v = block._find_var_recursive(name)
                if v is not None and v.dtype == "float32":
                    v.dtype = "bfloat16"
        elif op.type in amp_lists.black_list:
            cast_input(op, "float32", ("bfloat16",))
        new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    return program


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=1.0,
                 use_dynamic_loss_scaling=False, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.8):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = init_loss_scaling  # parity only; bf16 needs none

    def backward(self, loss, **kwargs):
        rewrite_program_bf16(loss.block.program, self._amp_lists)
        return self._optimizer.backward(loss, **kwargs)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        rewrite_program_bf16(loss.block.program, self._amp_lists)
        return self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=False):
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
    )
