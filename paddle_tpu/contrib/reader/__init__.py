"""Contrib readers (reference: ``python/paddle/fluid/contrib/reader/``
— the C++-thread ctr_reader and the distributed batch reader)."""

from .ctr_reader import ctr_reader  # noqa: F401
from .distributed_reader import distributed_batch_reader  # noqa: F401

__all__ = ["ctr_reader", "distributed_batch_reader"]
