"""CTR reader (reference: ``contrib/reader/ctr_reader.py`` — a C++
thread pool parsing svm/csv slot files into the blocking queue).

TPU redesign: the parse runs through the native MultiSlot parser +
dataset pipeline (``paddle_tpu.dataset``); this front keeps the
reference's entry point and yields feed dicts."""

__all__ = ["ctr_reader"]


def ctr_reader(feed_dict, file_type, file_format, dense_slot_index,
               sparse_slot_index, capacity, thread_num, batch_size,
               file_list, slots, name=None):
    """Returns a generator of batched feed dicts built by the MultiSlot
    dataset pipeline over ``file_list`` (the C++ ctr_reader's job)."""
    from ...dataset import DatasetFactory

    dataset = DatasetFactory().create_dataset("QueueDataset")
    dataset.set_use_var(feed_dict)
    dataset.set_batch_size(batch_size)
    dataset.set_thread(thread_num)
    dataset.set_filelist(list(file_list))

    def reader():
        for batch in dataset.batch_iterator():
            yield batch

    return reader
