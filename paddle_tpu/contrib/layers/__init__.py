from .nn import fused_elemwise_activation  # noqa: F401
from .rnn_impl import (BasicGRUUnit, BasicLSTMUnit, basic_gru,  # noqa: F401
                       basic_lstm)

__all__ = ["fused_elemwise_activation", "BasicGRUUnit", "BasicLSTMUnit",
           "basic_gru", "basic_lstm"]
