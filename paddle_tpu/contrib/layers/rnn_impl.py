"""Basic RNN building blocks (reference:
``python/paddle/fluid/contrib/layers/rnn_impl.py`` — BasicLSTMUnit /
BasicGRUUnit cells built from basic ops, plus the multi-layer
``basic_lstm`` / ``basic_gru`` drivers).

TPU redesign: the cells are thin composites over basic ops (one [x,h]
matmul per step — MXU-shaped); the drivers run the framework's
scan-based lstm/gru ops per layer/direction over padded batch-first
[B, T, D] input with an optional ``sequence_length`` mask (the LoD
replacement).  Initial states follow the reference layout
[num_layers*dirs, B, H]."""

import paddle_tpu as fluid
from ...dygraph.layers import Layer
from ...param_attr import ParamAttr

__all__ = ["BasicGRUUnit", "BasicLSTMUnit", "basic_gru", "basic_lstm"]


def _act(name, default):
    """Resolve an activation given as None (default), a name string, or a
    callable layer function."""
    from ...layers import ops as _ops

    if name is None:
        return getattr(_ops, default)
    if callable(name):
        return name
    return getattr(_ops, str(name))


class BasicLSTMUnit(Layer):
    """One LSTM step on [B, D] input + [B, H] states (reference
    rnn_impl.py:622, a dygraph.Layer subclass like the reference):
    gates from one fc over [x, h]."""

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32"):
        super().__init__(name_scope)
        self._hidden = int(hidden_size)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_act = _act(gate_activation, "sigmoid")
        self._act = _act(activation, "tanh")
        self._forget_bias = float(forget_bias)

    def forward(self, input, pre_hidden, pre_cell):
        concat = fluid.layers.concat([input, pre_hidden], axis=1)
        gates = fluid.layers.fc(
            concat, size=4 * self._hidden, param_attr=self._param_attr,
            bias_attr=self._bias_attr)
        i, f, g, o = fluid.layers.split(gates, 4, dim=1)
        i = self._gate_act(i)
        f = self._gate_act(fluid.layers.scale(f, bias=self._forget_bias))
        o = self._gate_act(o)
        g = self._act(g)
        new_cell = fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(f, pre_cell),
            fluid.layers.elementwise_mul(i, g))
        new_hidden = fluid.layers.elementwise_mul(o, self._act(new_cell))
        return new_hidden, new_cell


class BasicGRUUnit(Layer):
    """One GRU step on [B, D] input + [B, H] state (reference
    rnn_impl.py:22, a dygraph.Layer subclass like the reference)."""

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype="float32"):
        super().__init__(name_scope)
        self._hidden = int(hidden_size)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_act = _act(gate_activation, "sigmoid")
        self._act = _act(activation, "tanh")

    def forward(self, input, pre_hidden):
        concat = fluid.layers.concat([input, pre_hidden], axis=1)
        ur = fluid.layers.fc(concat, size=2 * self._hidden,
                             param_attr=self._param_attr,
                             bias_attr=self._bias_attr)
        u, r = fluid.layers.split(self._gate_act(ur), 2, dim=1)
        cand_in = fluid.layers.concat(
            [input, fluid.layers.elementwise_mul(r, pre_hidden)], axis=1)
        c = self._act(fluid.layers.fc(
            cand_in, size=self._hidden, param_attr=self._param_attr,
            bias_attr=self._bias_attr))
        one_minus_u = fluid.layers.scale(u, scale=-1.0, bias=1.0)
        return fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(u, pre_hidden),
            fluid.layers.elementwise_mul(one_minus_u, c))


def _layer_io(input, batch_first):
    if not batch_first:
        input = fluid.layers.transpose(input, [1, 0, 2])
    return input


def _init_state(init, idx):
    """Slice [num_layers*dirs, B, H] initial state to [B, H] for slot
    ``idx`` (reference rnn_impl per-layer slicing); None stays None."""
    if init is None:
        return None
    return fluid.layers.squeeze(
        fluid.layers.slice(init, axes=[0], starts=[idx], ends=[idx + 1]),
        [0])


def _last_step(h, d, sequence_length):
    """Final state of a direction: forward ends at t=len-1; the REVERSE
    scan's outputs are flipped back to input time order by the lstm/gru
    op, so its final state sits at t=0."""
    if d == 0:
        return fluid.layers.sequence_last_step(h, seq_len=sequence_length)
    return fluid.layers.sequence_first_step(h)


def basic_lstm(input, init_hidden, init_cell, hidden_size, num_layers=1,
               sequence_length=None, dropout_prob=0.0, bidirectional=False,
               batch_first=True, param_attr=None, bias_attr=None,
               gate_activation=None, activation=None, dtype="float32",
               name="basic_lstm"):
    """Multi-layer (optionally bidirectional) LSTM over padded input
    (reference rnn_impl.py:353).  Returns (rnn_out, last_hidden,
    last_cell) with rnn_out [B, T, H*dirs] (batch_first) and last states
    [num_layers*dirs, B, H]."""
    x = _layer_io(input, batch_first)
    dirs = 2 if bidirectional else 1
    lasts_h, lasts_c = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            proj = fluid.layers.fc(
                x, size=4 * hidden_size, num_flatten_dims=2,
                bias_attr=False,
                param_attr=ParamAttr(name="%s_l%d_d%d_x" % (name, layer,
                                                            d)))
            h, c = fluid.layers.dynamic_lstm(
                proj, size=4 * hidden_size, use_peepholes=False,
                is_reverse=(d == 1), seq_len=sequence_length,
                h_0=_init_state(init_hidden, idx),
                c_0=_init_state(init_cell, idx),
                param_attr=ParamAttr(name="%s_l%d_d%d_h" % (name, layer,
                                                            d)),
                bias_attr=ParamAttr(name="%s_l%d_d%d_b" % (name, layer,
                                                           d)))
            outs.append(h)
            lasts_h.append(_last_step(h, d, sequence_length))
            lasts_c.append(_last_step(c, d, sequence_length))
        x = outs[0] if dirs == 1 else fluid.layers.concat(outs, axis=2)
        if dropout_prob:
            x = fluid.layers.dropout(
                x, dropout_prob,
                dropout_implementation="upscale_in_train")
    last_h = fluid.layers.stack(lasts_h, axis=0)
    last_c = fluid.layers.stack(lasts_c, axis=0)
    out = x if batch_first else fluid.layers.transpose(x, [1, 0, 2])
    return out, last_h, last_c


def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, param_attr=None, bias_attr=None,
              gate_activation=None, activation=None, dtype="float32",
              name="basic_gru"):
    """Multi-layer (optionally bidirectional) GRU over padded input
    (reference rnn_impl.py:139)."""
    x = _layer_io(input, batch_first)
    dirs = 2 if bidirectional else 1
    lasts_h = []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            proj = fluid.layers.fc(
                x, size=3 * hidden_size, num_flatten_dims=2,
                bias_attr=False,
                param_attr=ParamAttr(name="%s_l%d_d%d_x" % (name, layer,
                                                            d)))
            h = fluid.layers.dynamic_gru(
                proj, size=hidden_size, is_reverse=(d == 1),
                seq_len=sequence_length,
                h_0=_init_state(init_hidden, idx),
                param_attr=ParamAttr(name="%s_l%d_d%d_h" % (name, layer,
                                                            d)),
                bias_attr=ParamAttr(name="%s_l%d_d%d_b" % (name, layer,
                                                           d)))
            outs.append(h)
            lasts_h.append(_last_step(h, d, sequence_length))
        x = outs[0] if dirs == 1 else fluid.layers.concat(outs, axis=2)
        if dropout_prob:
            x = fluid.layers.dropout(
                x, dropout_prob,
                dropout_implementation="upscale_in_train")
    last_h = fluid.layers.stack(lasts_h, axis=0)
    out = x if batch_first else fluid.layers.transpose(x, [1, 0, 2])
    return out, last_h
