"""contrib layers (reference:
``python/paddle/fluid/contrib/layers/nn.py`` — fused_elemwise_activation,
a hand-fused elementwise+activation kernel).

TPU-native: XLA fuses elementwise chains automatically, so the layer
simply emits the composed ops — same API, and the fusion the reference
hand-wrote falls out of the compiler."""

from ... import layers

__all__ = ["fused_elemwise_activation"]

_UNARY = {
    "relu": layers.relu,
    "sigmoid": lambda x: layers.sigmoid(x),
    "tanh": lambda x: layers.tanh(x),
    "scale": layers.scale,
}

_BINARY = {
    "elementwise_add": layers.elementwise_add,
    "elementwise_sub": layers.elementwise_sub,
    "elementwise_mul": layers.elementwise_mul,
}


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """Compose f1(f2(x, y)) or f2(x, f1(y)) per the reference contract:
    functor_list is [unary, binary] or [binary, unary]."""
    if not isinstance(functor_list, (list, tuple)) or \
            len(functor_list) != 2:
        raise ValueError("functor_list must hold exactly two functors")
    a, b = functor_list
    if a in _BINARY and b in _UNARY:
        # binary first then unary: f_u(f_b(x, y))
        mid = _BINARY[a](x, y, axis=axis) if a != "scale" else None
        out = (_UNARY[b](mid, scale=scale) if b == "scale"
               else _UNARY[b](mid))
    elif a in _UNARY and b in _BINARY:
        # unary applied to y first: f_b(x, f_u(y))
        uy = (_UNARY[a](y, scale=scale) if a == "scale"
              else _UNARY[a](y))
        out = _BINARY[b](x, uy, axis=axis)
    else:
        raise ValueError(
            "functor_list %r must pair one of %s with one of %s"
            % (functor_list, sorted(_BINARY), sorted(_UNARY)))
    return out
