"""contrib layers (reference:
``python/paddle/fluid/contrib/layers/nn.py`` — fused_elemwise_activation,
a hand-fused elementwise+activation kernel).

TPU-native: XLA fuses elementwise chains automatically, so the layer
simply emits the composed ops — same API, and the fusion the reference
hand-wrote falls out of the compiler."""

from ... import layers

__all__ = ["fused_elemwise_activation"]

_UNARY = {
    "relu": layers.relu,
    "sigmoid": lambda x: layers.sigmoid(x),
    "tanh": lambda x: layers.tanh(x),
    "scale": layers.scale,
}

_BINARY = {
    "elementwise_add": layers.elementwise_add,
    "elementwise_sub": layers.elementwise_sub,
    "elementwise_mul": layers.elementwise_mul,
}


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """Reference contract (``fused_elemwise_activation_op.h``:
    IsBinaryCompound keys on functor_list[0]):

    * ``[binary, unary]`` → Binary(x, Unary(y))
    * ``[unary, binary]`` → Unary(Binary(x, y))

    A comma-joined string ('elementwise_add,relu') is accepted like the
    reference."""
    if isinstance(functor_list, str):
        functor_list = functor_list.split(",")
    if not isinstance(functor_list, (list, tuple)) or \
            len(functor_list) != 2:
        raise ValueError("functor_list must hold exactly two functors")
    a, b = (f.strip() for f in functor_list)

    def unary(fn_name, v):
        return (_UNARY[fn_name](v, scale=scale) if fn_name == "scale"
                else _UNARY[fn_name](v))

    if a in _BINARY and b in _UNARY:
        out = _BINARY[a](x, unary(b, y), axis=axis)
    elif a in _UNARY and b in _BINARY:
        out = unary(a, _BINARY[b](x, y, axis=axis))
    else:
        raise ValueError(
            "functor_list %r must pair one of %s with one of %s"
            % (functor_list, sorted(_BINARY), sorted(_UNARY)))
    return out
