"""Seq2seq decoder DSL (reference:
``python/paddle/fluid/contrib/decoder/beam_search_decoder.py`` —
InitState:43, StateCell:159, TrainingDecoder:384, BeamSearchDecoder:523).

TPU redesign: the reference drives LoD-ragged beams (sequence_expand over
scores' LoD, lod_reset, ragged arrays).  Here beams are DENSE — ids and
scores are [B, K], per-beam states [B*K, H] — exactly the padded/static
convention of ``layers.beam_search``/``beam_search_decode``
(ops/beam_search.py), with parent-index gathers replacing the LoD
expansion.  TrainingDecoder runs on DynamicRNN (masked scan); the
BeamSearchDecoder's loop is a bounded ``While`` whose arrays are the
dense [B, K] step records.
"""

import contextlib

import paddle_tpu as fluid

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


class InitState:
    """Initial decoder state (reference :43): either an existing variable
    (``init``) or a to-be-created zero/constant state (``shape`` +
    ``value``) whose batch dim follows ``init_boot``."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is None and init_boot is None and shape is None:
            raise ValueError(
                "InitState needs init, or shape (+ optional init_boot)")
        self._init = init
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder

    def make_var(self, batch_ref=None):
        if self._init is not None:
            return self._init
        shape = list(self._shape or [])
        if batch_ref is not None and (not shape or shape[0] in (None, -1)):
            b = batch_ref.shape[0]
            shape = [b] + [d for d in shape[1:]]
        return fluid.layers.fill_constant(shape, self._dtype,
                                          float(self._value))


class StateCell:
    """Symbolic step cell (reference :159): named inputs + named states +
    an updater registered with ``@cell.state_updater`` that reads
    ``get_input``/``get_state`` and writes ``set_state``."""

    def __init__(self, inputs, states, out_state, name=None):
        self._inputs = dict(inputs)
        self._init_states = dict(states)
        self._state_names = list(states)
        self._out_state_name = out_state
        self._cur_states = {}
        self._next_states = {}
        self._updater = None
        self._decoder = None

    # decoder context ----------------------------------------------------
    def _enter_decoder(self, decoder):
        self._decoder = decoder

    def _leave_decoder(self, decoder):
        self._decoder = None

    # updater API --------------------------------------------------------
    def state_updater(self, updater):
        self._updater = updater

        def _decorator(cell):
            return updater(cell)

        return _decorator

    def get_input(self, input_name):
        if input_name not in self._inputs:
            raise ValueError("unknown input %r" % input_name)
        v = self._inputs[input_name]
        if v is None:
            raise ValueError("input %r has no value this step" % input_name)
        return v

    def get_state(self, state_name):
        if state_name not in self._cur_states:
            raise ValueError("unknown state %r" % state_name)
        return self._cur_states[state_name]

    def set_state(self, state_name, state_value):
        self._next_states[state_name] = state_value

    def compute_state(self, inputs):
        """Run the updater with this step's input values."""
        if self._updater is None:
            raise ValueError("no state_updater registered")
        for k, v in inputs.items():
            if k not in self._inputs:
                raise ValueError("unknown input %r" % k)
            self._inputs[k] = v
        self._next_states = {}
        self._updater(self)

    def update_states(self):
        """Commit set_state() values as the next step's states (the
        decoder in context wires the carry)."""
        if self._decoder is None:
            raise ValueError("update_states outside a decoder block")
        self._decoder._commit_states(self)

    def out_state(self):
        return self._next_states.get(
            self._out_state_name,
            self._cur_states.get(self._out_state_name))


class TrainingDecoder:
    """Teacher-forced decoder (reference :384) over DynamicRNN: states
    become rnn memories, ``step_input`` slices the target sequence, the
    updater runs per step."""

    def __init__(self, state_cell, name=None):
        self._state_cell = state_cell
        self._rnn = fluid.layers.DynamicRNN()
        self._in_block = False

    @property
    def state_cell(self):
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._rnn

    @property
    def type(self):
        return "training"

    @contextlib.contextmanager
    def block(self):
        self._state_cell._enter_decoder(self)
        with self._rnn.block():
            self._in_block = True
            # states → rnn memories (init from InitState)
            self._memories = {}
            for name in self._state_cell._state_names:
                ist = self._state_cell._init_states[name]
                if ist.value is not None:
                    mem = self._rnn.memory(init=ist.value,
                                           need_reorder=ist.need_reorder)
                else:
                    shape = list(ist._shape or [])
                    mem = self._rnn.memory(shape=shape[1:] or shape,
                                           value=float(ist._value))
                self._memories[name] = mem
                self._state_cell._cur_states[name] = mem
            yield
            self._in_block = False
        self._state_cell._leave_decoder(self)

    def step_input(self, x, lengths=None):
        """``lengths`` [B] marks each sequence's valid steps (the LoD
        replacement); None means every row runs the full padded length
        (the fill op is emitted in x's own block, outside the rnn)."""
        if lengths is None and self._rnn.lengths is None:
            prog = x.block.program
            cur = prog.current_block_idx
            prog.current_block_idx = x.block.idx
            try:
                lengths = fluid.layers.fill_constant_batch_size_like(
                    x, [-1], "int64", float(x.shape[1]))
            finally:
                prog.current_block_idx = cur
        return self._rnn.step_input(x, lengths=lengths)

    def static_input(self, x):
        return self._rnn.static_input(x)

    def output(self, *outputs):
        self._rnn.output(*outputs)

    def _commit_states(self, cell):
        for name, new in cell._next_states.items():
            self._rnn.update_memory(self._memories[name], new)
            cell._cur_states[name] = new

    def __call__(self, *args, **kwargs):
        return self._rnn(*args, **kwargs)


class BeamSearchDecoder:
    """Beam decoder (reference :523), dense-beam redesign:
    ``init_ids``/``init_scores`` are [B, K] (beam 0 live, others -inf);
    states are [B*K, H] and are re-gathered by the parent index each
    step (the LoD sequence_expand role).  ``decode()`` builds the
    standard loop; ``__call__`` backtraces to ([B, K, max_len] ids,
    [B, K] scores)."""

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None):
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = int(target_dict_dim)
        self._word_dim = int(word_dim)
        self._input_var_dict = dict(input_var_dict or {})
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._sparse_emb = sparse_emb
        self._name = name or "beam_search_decoder"
        self._arrays = {}         # id(array) → (array, update_var)
        self._built = False

    @property
    def state_cell(self):
        return self._state_cell

    @property
    def type(self):
        return "beam_search"

    @contextlib.contextmanager
    def block(self):
        """Open the decode loop.  Inside: read_array for loop-carried
        beams, the step computation, update_array for next-step values;
        on exit the arrays' step records are written and the counter
        advances."""
        L = fluid.layers
        B, K = self._init_ids.shape
        self._B, self._K = int(B), int(K)
        self._state_cell._enter_decoder(self)

        self._counter = L.fill_constant([1], "int32", 0.0)
        limit = L.fill_constant([1], "int32", float(self._max_len))
        self._cond = L.less_than(self._counter, limit)
        self._limit = limit

        # per-beam state carry vars: tile [B, H] inits to [B*K, H]
        self._state_vars = {}
        for name in self._state_cell._state_names:
            ist = self._state_cell._init_states[name]
            init = ist.make_var(batch_ref=self._init_ids)
            tiled = L.reshape(
                L.expand(L.unsqueeze(init, axes=[1]),
                         expand_times=[1, self._K, 1]),
                shape=[self._B * self._K, -1])
            carry = L.assign(tiled)
            self._state_vars[name] = carry

        self._row_offset = L.reshape(
            L.range(0, self._B * self._K, self._K, "int32"),
            shape=[self._B, 1])

        # parent record for every step (custom loops record it via
        # update_parents; decode() does so itself)
        zero = L.fill_constant([1], "int32", 0.0)
        self._parents_array = L.array_write(
            L.assign(L.cast(self._init_ids, "int32")), zero,
            capacity=self._max_len)

        self._while = L.While(self._cond)
        self._pending_writes = []
        self._parent = None
        self._alive = None
        with self._while.block():
            for name, carry in self._state_vars.items():
                self._state_cell._cur_states[name] = carry
            yield
            # epilogue: write this step's records, advance, re-check.
            # ANDing with the CURRENT cond keeps an early_stop() False
            # sticky instead of clobbering it
            for array, value in self._pending_writes:
                L.array_write(value, self._counter, array)
            L.increment(self._counter, in_place=True)
            keep = L.logical_and(L.less_than(self._counter, self._limit),
                                 self._cond)
            if self._alive is not None:
                keep = L.logical_and(keep, self._alive)
            L.assign(keep, output=self._cond)
        self._state_cell._leave_decoder(self)
        self._built = True

    @contextlib.contextmanager
    def _parent_block(self):
        """Emit ops into the block ENCLOSING the while (the reference's
        _parent_block(): arrays and their init writes live pre-loop)."""
        prog = fluid.default_main_program()
        cur = prog.current_block_idx
        prog.current_block_idx = prog.block(cur).parent_idx
        try:
            yield
        finally:
            prog.current_block_idx = cur

    def read_array(self, init, is_ids=False, is_scores=False):
        """A loop-carried [B, K] value: pre-loop it holds ``init``; each
        step's update_array() both records it into the step array and
        carries it to the next iteration."""
        L = fluid.layers
        with self._parent_block():
            carry = L.assign(init)
            zero = L.fill_constant([1], "int32", 0.0)
            array = L.array_write(L.assign(init), zero,
                                  capacity=self._max_len)
        self._arrays[id(carry)] = (array, carry)
        if is_ids:
            self._ids_carry, self._ids_array = carry, array
        if is_scores:
            self._scores_carry, self._scores_array = carry, array
        return carry

    def update_array(self, array, value):
        """Record ``value`` as this step's entry of ``array``'s step
        records and carry it into the next iteration."""
        arr, carry = self._arrays[id(array)]
        self._pending_writes.append((arr, value))
        fluid.layers.assign(value, output=carry)

    def early_stop(self):
        fluid.layers.fill_constant([1], "bool", 0.0, out=self._cond)

    def update_parents(self, parent):
        """Record this step's [B, K] parent-beam indices (custom block()
        loops must call this once per step so the final backtrace —
        ``decoder()`` → beam_search_decode — can replay the tree)."""
        self._parent = parent
        self._pending_writes.append((self._parents_array, parent))

    def _commit_states(self, cell):
        """Gather each state by the step's parent beams and carry it."""
        L = fluid.layers
        parent = self._parent
        for name, new in cell._next_states.items():
            if parent is not None:
                gp = L.reshape(
                    L.elementwise_add(parent, self._row_offset),
                    shape=[self._B * self._K])
                new = L.gather(new, gp)
            L.assign(new, output=self._state_vars[name])
            cell._cur_states[name] = self._state_vars[name]

    def decode(self):
        """The standard decode step (reference :653), dense-beam form."""
        L = fluid.layers
        with self.block():
            prev_ids = self.read_array(self._init_ids, is_ids=True)
            prev_scores = self.read_array(self._init_scores,
                                          is_scores=True)

            flat_ids = L.reshape(L.cast(prev_ids, "int64"),
                                 shape=[self._B * self._K])
            emb = L.embedding(flat_ids,
                              size=[self._target_dict_dim, self._word_dim],
                              param_attr=fluid.ParamAttr(
                                  name=self._name + "_emb"))
            feed_dict = {}
            for in_name in self._state_cell._inputs:
                if in_name in self._input_var_dict:
                    feed_dict[in_name] = self._input_var_dict[in_name]
                else:
                    feed_dict[in_name] = emb
            self._state_cell.compute_state(inputs=feed_dict)
            current_state = self._state_cell.out_state()
            logits = L.fc(current_state, size=self._target_dict_dim,
                          param_attr=fluid.ParamAttr(
                              name=self._name + "_out_w"),
                          bias_attr=fluid.ParamAttr(
                              name=self._name + "_out_b"))
            logp = L.log_softmax(logits)
            logp3 = L.reshape(
                logp, shape=[self._B, self._K, self._target_dict_dim])
            sel_ids, sel_scores, parent = L.beam_search(
                prev_ids, prev_scores, None, logp3,
                beam_size=self._beam_size, end_id=self._end_id,
                is_accumulated=False, return_parent_idx=True)
            self.update_parents(parent)

            # alive check (the reference's is_empty early stop)
            end_const = L.fill_constant([self._B, self._K], "int32",
                                        float(self._end_id))
            alive = L.cast(L.not_equal(sel_ids, end_const), "int32")
            self._alive = L.greater_than(
                L.reduce_sum(alive), L.fill_constant([1], "int32", 0.0))

            self._state_cell.update_states()
            self.update_array(prev_ids, sel_ids)
            self.update_array(prev_scores, sel_scores)
        return self

    def __call__(self):
        if not self._built:
            raise ValueError("call decode() (or build a block()) first")
        if not hasattr(self, "_ids_array"):
            raise ValueError(
                "no beam arrays recorded: a custom block() loop must "
                "read_array(init_ids, is_ids=True) / read_array(..., "
                "is_scores=True) and call update_parents() each step")
        return fluid.layers.beam_search_decode(
            self._ids_array, self._scores_array, self._parents_array,
            beam_size=self._beam_size, end_id=self._end_id)
