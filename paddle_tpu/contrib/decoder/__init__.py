"""Decoder DSL (reference: ``python/paddle/fluid/contrib/decoder/``)."""

from .beam_search_decoder import (BeamSearchDecoder, InitState,  # noqa: F401
                                  StateCell, TrainingDecoder)

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]
