"""Model statistics summary (reference
``python/paddle/fluid/contrib/model_stat.py``: ``summary(main_prog)``
prints a per-op table of TYPE / INPUT / OUTPUT / PARAMs / FLOPs plus
totals).  Built on the slim GraphWrapper's shared per-op FLOPs
accounting; no prettytable dependency (plain column formatting)."""

import numpy as np

__all__ = ["summary"]

_COUNTED = ("conv2d", "depthwise_conv2d", "mul", "matmul", "batch_norm",
            "relu", "sigmoid", "tanh", "pool2d", "elementwise_add",
            "elementwise_mul")


def _fmt_shape(shapes):
    if not shapes:
        return "-"
    s = shapes[0]
    return str(tuple(int(d) for d in s)) if s else "-"


def summary(main_prog):
    """Print (and return as a list of rows) the per-op stats table for
    the counted op set; mirrors the reference's output shape
    (model_stat.py docstring table)."""
    from .slim.graph import GraphWrapper, op_flops

    g = GraphWrapper(main_prog)
    rows = []
    total_params = 0
    total_flops = 0
    for op in g.ops():
        t = op.type()
        if t not in _COUNTED:
            continue
        params = int(sum(
            np.prod([d for d in p.shape() if d > 0]) or 0
            for p in g.get_param_by_op(op)))
        flops = op_flops(op)
        ins = [v.shape() for v in op.all_inputs()
               if not v.is_parameter()]
        outs = [v.shape() for v in op.all_outputs()]
        rows.append((len(rows), t, _fmt_shape(ins), _fmt_shape(outs),
                     params, flops))
        total_params += params
        total_flops += flops

    widths = (5, 12, 18, 18, 10, 14)
    heads = ("No.", "TYPE", "INPUT", "OUTPUT", "PARAMs", "FLOPs")
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    print(sep)
    print("|" + "|".join(" %*s " % (w, h)
                         for w, h in zip(widths, heads)) + "|")
    print(sep)
    for r in rows:
        print("|" + "|".join(" %*s " % (w, str(c))
                             for w, c in zip(widths, r)) + "|")
    print(sep)
    print("Total PARAMs: %d(%.4fG)" % (total_params, total_params / 1e9))
    print("Total FLOPs: %d(%.2fG)" % (total_flops, total_flops / 1e9))
    return rows
