"""Op-frequency statistics (reference:
``python/paddle/fluid/contrib/op_frequence.py`` — single-op counts plus
producer→consumer adjacent-pair counts, both sorted descending).

Used to decide fusion/kernel priorities; on TPU it doubles as a quick
"what will XLA see" census before profiling."""

from ..framework import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_2_op_freq): descending-sorted LISTS of
    (key, count) tuples — iterable as ``for op_type, n in uni_op_freq``
    like the reference docstring shows — with adjacency keys
    'producer->consumer' (pairs linked through non-parameter
    dataflow)."""
    if not isinstance(program, Program):
        raise TypeError(
            "op_freq_statistic requires a Program, got %s"
            % (type(program),))

    uni = {}
    adj = {}
    params = {p.name for p in program.global_block().all_parameters()}
    producer = {}

    for op in program.global_block().ops:
        uni[op.type] = uni.get(op.type, 0) + 1
        for name in op.input_arg_names:
            src = producer.get(name)
            if src is not None and name not in params:
                key = "%s->%s" % (src, op.type)
                adj[key] = adj.get(key, 0) + 1
        for name in op.output_arg_names:
            if name and name not in params:
                producer[name] = op.type

    uni_sorted = sorted(uni.items(), key=lambda kv: kv[1], reverse=True)
    adj_sorted = sorted(adj.items(), key=lambda kv: kv[1], reverse=True)
    return uni_sorted, adj_sorted
