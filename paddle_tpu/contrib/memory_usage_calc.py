"""Estimate a program's training memory footprint (reference:
``python/paddle/fluid/contrib/memory_usage_calc.py`` — sums var bytes
over the main block, scaling -1 dims by batch_size).

TPU-native bounds: the LOWER bound counts each op-output var once
(XLA's fusion + buffer reuse means transient elementwise intermediates
mostly never materialize — closer to reality on TPU than on the
reference's CUDA allocator); the UPPER bound multiplies by 1.7 to cover
XLA's scratch/padding/donation slack, in place of the reference's
empirical 1.5x DEBUG factor.  Same return contract:
``(lower, upper, unit_str)``."""

import numpy as np

from ..framework import Program

__all__ = ["memory_usage"]

_DTYPE_BYTES = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
    "int8": 1, "uint8": 1, "int16": 2, "int32": 4, "int64": 8,
    "bool": 1,
}

_UNITS = ["B", "KB", "MB", "GB"]


def memory_usage(program, batch_size):
    """Returns (lower, upper, unit) estimated for one training step."""
    if not isinstance(program, Program):
        raise TypeError(
            "memory_usage requires a Program, got %s" % (type(program),))
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")

    total = 0.0
    seen = {"@EMPTY@"}
    block = program.global_block()
    for op in block.ops:
        for name in op.output_arg_names:
            if not name or name in seen:
                continue
            seen.add(name)
            var = block.vars.get(name)
            if var is None or var.shape is None:
                continue
            count = 1
            neg = 0
            for d in var.shape:
                if d is None or d < 0:
                    neg += 1
                    if neg > 1:
                        raise ValueError(
                            "var %r has more than one dynamic dim" % name)
                    count *= batch_size * max(1, -int(d or -1))
                else:
                    count *= int(d)
            total += count * _DTYPE_BYTES.get(str(var.dtype), 4)

    lower, upper = total, total * 1.7
    unit = 0
    while upper >= 1024.0 and unit < len(_UNITS) - 1:
        lower /= 1024.0
        upper /= 1024.0
        unit += 1
    return lower, upper, _UNITS[unit]
