"""Quantization-aware training transform.

Reference: ``python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py`` (``TransformForTrainingPass``: insert
quant/dequant ops on every input of quantizable ops — conv2d,
depthwise_conv2d, mul — weights with ``abs_max``, activations with
``moving_average_abs_max``) and ``contrib/quantize/quantize_transpiler.py``.

TPU-native: the inserted ops are the *fused* quantize+dequantize
simulators (ops/quantize.py) so the transformed program stays float
end-to-end (XLA fuses the round/clip chain into neighbours) while the
straight-through grad ops make training quantization-aware.  Run this
BEFORE ``append_backward``/``minimize`` (same contract as the reference
pass operating on the forward IrGraph).
"""

from paddle_tpu.initializer import ConstantInitializer

# which input slots of each quantizable op get quantized
_QUANT_SLOTS = {
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
    "mul": ("X", "Y"),
    "matmul": ("X", "Y"),
}

QUANTIZABLE_OP_TYPES = tuple(_QUANT_SLOTS)


class TransformForTraining:
    """Insert fake quant-dequant ops ahead of quantizable ops."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="abs_max", moving_rate=0.9):
        if activation_quantize_type not in ("moving_average_abs_max",
                                            "abs_max"):
            raise ValueError(
                "unsupported activation_quantize_type %r"
                % activation_quantize_type)
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(
                "unsupported weight_quantize_type %r" % weight_quantize_type)
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = float(moving_rate)

    def apply(self, program, startup_program=None):
        """Rewrites `program` in place; returns the number of quantized
        input slots.  `startup_program` is required for moving-average
        activation quantization (it receives the scale-state
        initializers)."""
        if (startup_program is None
                and self.activation_quantize_type
                == "moving_average_abs_max"):
            raise ValueError(
                "moving_average_abs_max needs startup_program to "
                "initialize scale state (pass it to apply(), or use "
                "activation_quantize_type='abs_max')")
        block = program.global_block()
        quantized = {}  # var name -> dequantized var name
        count = 0
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in _QUANT_SLOTS or op.attrs.get("__quant_skip__"):
                i += 1
                continue
            for slot in _QUANT_SLOTS[op.type]:
                names = op.inputs.get(slot)
                if not names:
                    continue
                name = names[0]
                if name.endswith(".quant_dequant"):
                    continue  # already transformed (idempotent re-apply)
                if name in quantized:
                    op.inputs[slot] = [quantized[name]]
                    continue
                var = block._find_var_recursive(name)
                if var is None:
                    continue
                is_weight = getattr(var, "persistable", False) or \
                    type(var).__name__ == "Parameter"
                n_new = self._insert_quant_dequant(
                    block, i, name, var, is_weight, startup_program)
                quantized[name] = name + ".quant_dequant"
                op.inputs[slot] = [quantized[name]]
                i += n_new
                count += 1
            i += 1
        if count:
            program._bump_version()
        return count

    def _insert_quant_dequant(self, block, idx, name, var, is_weight,
                              startup_program):
        """Insert the quant-dequant op at `idx`; returns #ops inserted."""
        out_name = name + ".quant_dequant"
        out = block.create_var(name=out_name, shape=var.shape,
                               dtype=var.dtype)
        out.stop_gradient = False
        channel_wise = (is_weight
                        and getattr(self, "weight_quantize_type",
                                    "abs_max") == "channel_wise_abs_max"
                        and var.shape and len(var.shape) >= 2)
        scale_shape = ((var.shape[0],) if channel_wise else (1,))
        scale = block.create_var(
            name=name + ".quant_scale", shape=scale_shape,
            dtype="float32", persistable=True)
        scale.stop_gradient = True

        bits = self.weight_bits if is_weight else self.activation_bits
        use_ma = (not is_weight
                  and self.activation_quantize_type
                  == "moving_average_abs_max")
        if not use_ma:
            block._insert_op(
                idx,
                type="fake_channel_wise_quantize_dequantize_abs_max"
                     if channel_wise
                     else "fake_quantize_dequantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [out_name], "OutScale": [scale.name]},
                attrs={"bit_length": bits},
            )
            return 1

        accum = block.create_var(
            name=name + ".quant_accum", shape=(1,), dtype="float32",
            persistable=True)
        state = block.create_var(
            name=name + ".quant_state", shape=(1,), dtype="float32",
            persistable=True)
        for v, init in ((scale, 1.0), (accum, 0.0), (state, 0.0)):
            v.stop_gradient = True
            if startup_program is not None:
                sb = startup_program.global_block()
                sv = sb.create_var(name=v.name, shape=v.shape,
                                   dtype=v.dtype, persistable=True)
                ConstantInitializer(init)(sv, sb)
        block._insert_op(
            idx,
            type="fake_quantize_dequantize_moving_average_abs_max",
            inputs={"X": [name], "InScale": [scale.name],
                    "InAccum": [accum.name], "InState": [state.name]},
            outputs={"Out": [out_name], "OutScale": [scale.name],
                     "OutAccum": [accum.name], "OutState": [state.name]},
            attrs={"bit_length": bits, "moving_rate": self.moving_rate},
        )
        return 1


_FAKE_QDQ_TYPES = (
    "fake_quantize_dequantize_abs_max",
    "fake_quantize_dequantize_moving_average_abs_max",
    "fake_channel_wise_quantize_dequantize_abs_max",
)


def _is_weight_var(var):
    return var is not None and (getattr(var, "persistable", False)
                                or type(var).__name__ == "Parameter")


class QuantizationFreezePass:
    """Freeze a QAT-trained program for deployment (reference
    ``slim/quantization/quantization_pass.py`` ``QuantizationFreezePass``).

    TPU-native rewrite, two halves:

    * **weights** — the trained fp32 weight is converted to int8 STORAGE
      in the scope (round(W/scale*bin_cnt), the reference's
      ``_quant``), the weight var's dtype flips to int8, and the fake
      quant-dequant op is replaced by ``fake_dequantize_max_abs`` — so
      the deployed checkpoint and HBM hold int8 weights, with the
      dequant multiply fused into the consumer by XLA.  This is where
      int8 actually pays on TPU: 4x smaller persistables.
    * **activations** — the fake quant-dequant op is REMOVED; its
      trained scale is stamped onto consumer ops as ``Input_scale`` +
      ``quantization_type`` attrs (the record a downstream int8 engine
      reads; reference freeze does the same before the int8-kernel
      swap).  The float graph then computes at full precision —
      matching the reference, where dequantized activations flow into
      the next op.
    """

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, weight_quantize_type="abs_max"):
        self._scope = scope
        self._place = place
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)

    def apply(self, program, weights_only=False):
        import jax.numpy as jnp
        import numpy as np

        scope = self._scope
        if scope is None:
            from paddle_tpu.executor import global_scope

            scope = global_scope()
        block = program.global_block()
        changed = 0
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in _FAKE_QDQ_TYPES:
                i += 1
                continue
            x_name = op.inputs["X"][0]
            out_name = op.outputs["Out"][0]
            scale_name = op.outputs["OutScale"][0]
            xvar = block._find_var_recursive(x_name)
            bits = int(op.attrs.get("bit_length", 8))
            bin_cnt = float((1 << (bits - 1)) - 1)
            if _is_weight_var(xvar):
                channel_wise = op.type.startswith("fake_channel_wise")
                w = np.asarray(scope.get(x_name), dtype=np.float32)
                if channel_wise:
                    scale = np.abs(w.reshape(w.shape[0], -1)).max(axis=1)
                    scale = np.maximum(scale, 1e-8)
                    s_b = scale.reshape((-1,) + (1,) * (w.ndim - 1))
                    wq = np.clip(np.round(w / s_b * bin_cnt), -bin_cnt,
                                 bin_cnt).astype(np.int8)
                    scope.set(scale_name, jnp.asarray(
                        scale, dtype=jnp.float32))
                else:
                    scale = float(np.max(np.abs(w)))
                    if scale <= 0:
                        scale = 1e-8
                    wq = np.clip(np.round(w / scale * bin_cnt), -bin_cnt,
                                 bin_cnt).astype(np.int8)
                    scope.set(scale_name,
                              jnp.asarray([scale], dtype=jnp.float32))
                scope.set(x_name, jnp.asarray(wq))
                from paddle_tpu import core

                xvar.dtype = core.convert_np_dtype_to_dtype_("int8")
                svar = block._find_var_recursive(scale_name)
                if svar is not None:
                    svar.persistable = True
                block._remove_op(i)
                if channel_wise:
                    block._insert_op(
                        i,
                        type="fake_channel_wise_dequantize_max_abs",
                        inputs={"X": [x_name], "Scales": [scale_name]},
                        outputs={"Out": [out_name]},
                        attrs={"quant_bits": [bits]},
                    )
                else:
                    block._insert_op(
                        i,
                        type="fake_dequantize_max_abs",
                        inputs={"X": [x_name], "Scale": [scale_name]},
                        outputs={"Out": [out_name]},
                        attrs={"max_range": bin_cnt},
                    )
                i += 1
            elif weights_only:
                i += 1
                continue
            else:
                sv = scope.get(scale_name)
                scale_val = (float(np.asarray(sv).reshape(-1)[0])
                             if sv is not None else 0.0)
                block._remove_op(i)
                for later in block.ops[i:]:
                    for slot, names in later.inputs.items():
                        if out_name in names:
                            later.inputs[slot] = [
                                x_name if n == out_name else n
                                for n in names]
                            later.attrs["quantization_type"] = \
                                "qat_weight_int8"
                            later.attrs["Input_scale"] = scale_val
            changed += 1
        if changed:
            program._bump_version()
        return program


class QuantizationTranspiler(TransformForTraining):
    """``contrib/quantize/quantize_transpiler.py`` façade: the v1.5 entry
    point name, same transform."""

    def training_transpile(self, program, startup_program=None):
        return self.apply(program, startup_program)

    def freeze_program(self, program, place=None, fuse_bn=False, scope=None):
        """reference QuantizeTranspiler.freeze_program: rewrite the
        trained program for inference — int8 weight storage + dequant
        ops, activation scales recorded on consumers (see
        QuantizationFreezePass)."""
        return QuantizationFreezePass(
            scope=scope, place=place, weight_bits=self.weight_bits,
            activation_bits=self.activation_bits).apply(program)

    def convert_to_int8(self, program, place=None, scope=None):
        """reference QuantizeTranspiler.convert_to_int8: weight-only
        int8 storage conversion (activation fake-quant ops untouched)."""
        return QuantizationFreezePass(
            scope=scope, place=place, weight_bits=self.weight_bits,
            activation_bits=self.activation_bits).apply(
                program, weights_only=True)
