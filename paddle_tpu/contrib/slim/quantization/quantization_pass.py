"""Quantization-aware training transform.

Reference: ``python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py`` (``TransformForTrainingPass``: insert
quant/dequant ops on every input of quantizable ops — conv2d,
depthwise_conv2d, mul — weights with ``abs_max``, activations with
``moving_average_abs_max``) and ``contrib/quantize/quantize_transpiler.py``.

TPU-native: the inserted ops are the *fused* quantize+dequantize
simulators (ops/quantize.py) so the transformed program stays float
end-to-end (XLA fuses the round/clip chain into neighbours) while the
straight-through grad ops make training quantization-aware.  Run this
BEFORE ``append_backward``/``minimize`` (same contract as the reference
pass operating on the forward IrGraph).
"""

from paddle_tpu.initializer import ConstantInitializer

# which input slots of each quantizable op get quantized
_QUANT_SLOTS = {
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
    "mul": ("X", "Y"),
    "matmul": ("X", "Y"),
}

QUANTIZABLE_OP_TYPES = tuple(_QUANT_SLOTS)


class TransformForTraining:
    """Insert fake quant-dequant ops ahead of quantizable ops."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="abs_max", moving_rate=0.9):
        if activation_quantize_type not in ("moving_average_abs_max",
                                            "abs_max"):
            raise ValueError(
                "unsupported activation_quantize_type %r"
                % activation_quantize_type)
        if weight_quantize_type != "abs_max":
            raise ValueError(
                "unsupported weight_quantize_type %r" % weight_quantize_type)
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.activation_quantize_type = activation_quantize_type
        self.moving_rate = float(moving_rate)

    def apply(self, program, startup_program=None):
        """Rewrites `program` in place; returns the number of quantized
        input slots.  `startup_program` is required for moving-average
        activation quantization (it receives the scale-state
        initializers)."""
        if (startup_program is None
                and self.activation_quantize_type
                == "moving_average_abs_max"):
            raise ValueError(
                "moving_average_abs_max needs startup_program to "
                "initialize scale state (pass it to apply(), or use "
                "activation_quantize_type='abs_max')")
        block = program.global_block()
        quantized = {}  # var name -> dequantized var name
        count = 0
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in _QUANT_SLOTS or op.attrs.get("__quant_skip__"):
                i += 1
                continue
            for slot in _QUANT_SLOTS[op.type]:
                names = op.inputs.get(slot)
                if not names:
                    continue
                name = names[0]
                if name.endswith(".quant_dequant"):
                    continue  # already transformed (idempotent re-apply)
                if name in quantized:
                    op.inputs[slot] = [quantized[name]]
                    continue
                var = block._find_var_recursive(name)
                if var is None:
                    continue
                is_weight = getattr(var, "persistable", False) or \
                    type(var).__name__ == "Parameter"
                n_new = self._insert_quant_dequant(
                    block, i, name, var, is_weight, startup_program)
                quantized[name] = name + ".quant_dequant"
                op.inputs[slot] = [quantized[name]]
                i += n_new
                count += 1
            i += 1
        if count:
            program._bump_version()
        return count

    def _insert_quant_dequant(self, block, idx, name, var, is_weight,
                              startup_program):
        """Insert the quant-dequant op at `idx`; returns #ops inserted."""
        out_name = name + ".quant_dequant"
        out = block.create_var(name=out_name, shape=var.shape,
                               dtype=var.dtype)
        out.stop_gradient = False
        scale = block.create_var(
            name=name + ".quant_scale", shape=(1,), dtype="float32",
            persistable=True)
        scale.stop_gradient = True

        bits = self.weight_bits if is_weight else self.activation_bits
        use_ma = (not is_weight
                  and self.activation_quantize_type
                  == "moving_average_abs_max")
        if not use_ma:
            block._insert_op(
                idx,
                type="fake_quantize_dequantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [out_name], "OutScale": [scale.name]},
                attrs={"bit_length": bits},
            )
            return 1

        accum = block.create_var(
            name=name + ".quant_accum", shape=(1,), dtype="float32",
            persistable=True)
        state = block.create_var(
            name=name + ".quant_state", shape=(1,), dtype="float32",
            persistable=True)
        for v, init in ((scale, 1.0), (accum, 0.0), (state, 0.0)):
            v.stop_gradient = True
            if startup_program is not None:
                sb = startup_program.global_block()
                sv = sb.create_var(name=v.name, shape=v.shape,
                                   dtype=v.dtype, persistable=True)
                ConstantInitializer(init)(sv, sb)
        block._insert_op(
            idx,
            type="fake_quantize_dequantize_moving_average_abs_max",
            inputs={"X": [name], "InScale": [scale.name],
                    "InAccum": [accum.name], "InState": [state.name]},
            outputs={"Out": [out_name], "OutScale": [scale.name],
                     "OutAccum": [accum.name], "OutState": [state.name]},
            attrs={"bit_length": bits, "moving_rate": self.moving_rate},
        )
        return 1


class QuantizationTranspiler(TransformForTraining):
    """``contrib/quantize/quantize_transpiler.py`` façade: the v1.5 entry
    point name, same transform."""

    def training_transpile(self, program, startup_program=None):
        return self.apply(program, startup_program)

    def freeze_program(self, program, place=None, fuse_bn=False, scope=None):
        """reference QuantizeTranspiler.freeze_program: rewrite the
        trained program for inference — under XLA the fake-quant ops
        already carry their trained scales, and dequant folding is the
        compiler's job, so freezing is the identity transform here."""
        return program

    def convert_to_int8(self, program, place=None, scope=None):
        """reference QuantizeTranspiler.convert_to_int8: int8 weight
        storage is an HBM-footprint optimization the XLA path does not
        implement — raise rather than silently keep fp32."""
        raise NotImplementedError(
            "int8 weight conversion is not implemented on the TPU path; "
            "the fake-quant training transform (training_transpile) and "
            "slim QAT passes cover the quantization-aware capability")
