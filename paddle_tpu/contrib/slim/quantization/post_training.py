"""Post-training quantization with a calibration dataset.

Reference: ``paddle/fluid/inference/api/mkldnn_quantizer.cc`` — run the
fp32 model over calibration batches, gather per-tensor maxima, compute
scales (max / average of per-batch maxima), then rewrite the graph for
int8 execution.  TPU-native translation:

* calibration fetches every quantizable-op activation input through the
  normal Executor (one jit per calibration signature, cached);
* weights convert to int8 STORAGE + ``fake_dequantize_max_abs`` ops
  (via :class:`QuantizationFreezePass` — 4x smaller persistables, the
  dequant multiply fused into the consumer by XLA);
* activations get ``quantize_dequantize_fixed_scale`` ops carrying the
  calibrated scale, so the exported model's numerics include the
  quantization error an int8 deploy would see, and consumers carry the
  recorded ``Input_scale`` attr an int8 engine reads.
"""

import numpy as np

from .quantization_pass import (
    _QUANT_SLOTS,
    QuantizationFreezePass,
    TransformForTraining,
)

__all__ = ["PostTrainingQuantization"]


class PostTrainingQuantization:
    """Calibrate-and-quantize an inference program.

    Parameters mirror the reference API shape: an executor, the program
    (or a model dir to load), its feed names and fetch targets, the
    scale algorithm (``abs_max`` = global max over batches, ``avg`` =
    mean of per-batch maxima) and an optional batch cap.
    """

    def __init__(self, executor, program=None, feed_names=None,
                 fetch_targets=None, model_dir=None, scope=None,
                 algo="abs_max", weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max", batch_nums=None):
        if algo not in ("abs_max", "avg"):
            raise ValueError("algo must be abs_max or avg, got %r" % algo)
        self._exe = executor
        self._algo = algo
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.weight_quantize_type = weight_quantize_type
        self._batch_nums = batch_nums
        if scope is None:
            from paddle_tpu.executor import global_scope

            scope = global_scope()
        self._scope = scope
        if program is None:
            if model_dir is None:
                raise ValueError("pass program+feed_names or model_dir")
            from paddle_tpu import io as fluid_io

            program, feed_names, fetch_targets = \
                fluid_io.load_inference_model(model_dir, executor)
        self._program = program
        self._feed_names = list(feed_names or [])
        self._fetch_targets = list(fetch_targets or [])

    # -- calibration --------------------------------------------------

    def _activation_targets(self):
        """(op_index, slot, var_name) for every non-persistable input of
        a quantizable op — the tensors whose dynamic range calibration
        must observe."""
        block = self._program.global_block()
        targets = []
        for idx, op in enumerate(block.ops):
            slots = _QUANT_SLOTS.get(op.type)
            if not slots or op.attrs.get("__quant_skip__"):
                continue
            for slot in slots:
                names = op.inputs.get(slot)
                if not names:
                    continue
                var = block._find_var_recursive(names[0])
                if var is None or getattr(var, "persistable", False) or \
                        type(var).__name__ == "Parameter":
                    continue
                targets.append((idx, slot, names[0]))
        return targets

    def quantize(self, data_reader):
        """Run calibration batches from ``data_reader`` (an iterable of
        feed dicts), compute activation scales, rewrite the program.
        Returns the quantized program."""
        targets = self._activation_targets()
        names = sorted({n for _, _, n in targets})
        maxima = {n: [] for n in names}
        n_batches = 0
        # calibration feeds carry only the model INPUTS — prune the
        # program to the observed tensors so label-consuming metric ops
        # (accuracy/loss in a test program) don't demand feeds
        calib_prog = self._program._prune(
            [n for n in self._feed_names], names)
        for feed in data_reader:
            outs = self._exe.run(calib_prog, feed=feed,
                                 fetch_list=names)
            for n, v in zip(names, outs):
                maxima[n].append(float(np.max(np.abs(np.asarray(v)))))
            n_batches += 1
            if self._batch_nums and n_batches >= self._batch_nums:
                break
        if not n_batches:
            raise ValueError("calibration reader yielded no batches")
        reduce = max if self._algo == "abs_max" else \
            (lambda xs: sum(xs) / len(xs))
        scales = {n: max(reduce(v), 1e-8) for n, v in maxima.items()}
        self._rewrite(targets, scales)
        return self._program

    # -- rewrite ------------------------------------------------------

    def _rewrite(self, targets, scales):
        import jax.numpy as jnp

        program, scope = self._program, self._scope
        block = program.global_block()

        # 1. weights → int8 storage + dequant: insert dynamic fake-qdq
        #    on weight slots, then freeze them (reads trained values)
        TransformForTraining(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            activation_quantize_type="abs_max",
            weight_quantize_type=self.weight_quantize_type).apply(program)
        # drop the activation fake-qdq ops that transform just added —
        # PTQ uses the calibrated FIXED scales instead
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type.startswith("fake_quantize_dequantize"):
                x_name = op.inputs["X"][0]
                xvar = block._find_var_recursive(x_name)
                if not (xvar is not None
                        and (getattr(xvar, "persistable", False)
                             or type(xvar).__name__ == "Parameter")):
                    out_name = op.outputs["Out"][0]
                    block._remove_op(i)
                    for later in block.ops[i:]:
                        for slot, ns in later.inputs.items():
                            if out_name in ns:
                                later.inputs[slot] = [
                                    x_name if n == out_name else n
                                    for n in ns]
                    continue
            i += 1
        QuantizationFreezePass(
            scope=scope, weight_bits=self.weight_bits,
            activation_bits=self.activation_bits).apply(
                program, weights_only=True)

        # 2. activations → fixed-scale QDQ with the calibrated scale
        done = {}
        for _, _, name in targets:
            if name in done:
                continue
            scale_name = name + ".calib_scale"
            sv = block.create_var(name=scale_name, shape=(1,),
                                  dtype="float32", persistable=True)
            sv.stop_gradient = True
            scope.set(scale_name,
                      jnp.asarray([scales[name]], dtype=jnp.float32))
            out_name = name + ".calib_qdq"
            block.create_var(name=out_name, shape=None, dtype="float32")
            # insert immediately before the first consumer
            pos = next(i for i, op in enumerate(block.ops)
                       if any(name in ns for ns in op.inputs.values()))
            block._insert_op(
                pos,
                type="quantize_dequantize_fixed_scale",
                inputs={"X": [name], "InScale": [scale_name]},
                outputs={"Out": [out_name]},
                attrs={"bit_length": self.activation_bits},
            )
            done[name] = out_name
        # rewire every quantizable consumer and stamp the record attrs
        for op in block.ops:
            slots = _QUANT_SLOTS.get(op.type)
            if not slots or op.attrs.get("__quant_skip__"):
                continue
            for slot in slots:
                ns = op.inputs.get(slot)
                if ns and ns[0] in done:
                    op.inputs[slot] = [done[ns[0]]]
                    op.attrs["quantization_type"] = "post_training_int8"
                    op.attrs["Input_scale"] = float(scales[ns[0]])
        program._bump_version()

    # -- export -------------------------------------------------------

    def save_quantized_model(self, dirname, model_filename=None,
                             params_filename=None):
        from paddle_tpu import io as fluid_io

        return fluid_io.save_inference_model(
            dirname, self._feed_names, self._fetch_targets, self._exe,
            main_program=self._program, model_filename=model_filename,
            params_filename=params_filename)
