"""QuantizationStrategy for the slim Compressor (reference
``contrib/slim/quantization/quantization_strategy.py``: insert the QAT
fake-quant ops at ``start_epoch``, train, freeze + export int8 at
``end_epoch``)."""

from ..core import Strategy
from .quantization_pass import QuantizationFreezePass, TransformForTraining

__all__ = ["QuantizationStrategy"]


class QuantizationStrategy(Strategy):
    """Insert → train → freeze → save, driven by Compressor epochs.

    Contract (matches the reference's graph-then-compile ordering): give
    the Compressor the FORWARD program plus ``train_optimizer``; this
    strategy rewrites the forward graph in ``on_compression_begin`` and
    the compressor builds the optimizer afterwards, so gradients flow
    through the straight-through fake-quant ops.
    """

    def __init__(self, start_epoch=0, end_epoch=0, weight_bits=8,
                 activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="abs_max",
                 save_out_nodes=None, save_in_nodes=None,
                 float_model_save_path=None, int8_model_save_path=None):
        super().__init__(start_epoch, end_epoch)
        self.transform = TransformForTraining(
            weight_bits=weight_bits, activation_bits=activation_bits,
            activation_quantize_type=activation_quantize_type,
            weight_quantize_type=weight_quantize_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.save_out_nodes = save_out_nodes
        self.save_in_nodes = save_in_nodes
        self.float_model_save_path = float_model_save_path
        self.int8_model_save_path = int8_model_save_path

    def on_compression_begin(self, context):
        from paddle_tpu.framework import Program

        startup = context.get("startup_program")
        if startup is None:
            startup = Program()
            context["startup_program"] = startup
        n = self.transform.apply(context["program"], startup)
        context["quantized_slots"] = n
        # a test clone BEFORE the compressor minimizes: the freeze/export
        # target (reference uses the separate test graph the same way)
        context["quant_test_program"] = context["program"].clone(
            for_test=True)

    def on_epoch_end(self, context):
        if context["epoch"] != self.end_epoch:
            return
        test_prog = context.get("quant_test_program")
        if test_prog is None:
            return
        scope = context["scope"]
        if self.float_model_save_path and self.save_out_nodes:
            self._save(context, test_prog.clone(for_test=True), scope,
                       self.float_model_save_path)
        # freeze into a COPIED scope: QuantizationFreezePass rewrites
        # weight storage to int8 codes, and doing that to the live
        # training scope would make any epochs after end_epoch train on
        # raw quantization codes (silent ~bin_cnt-x weight corruption)
        from paddle_tpu.executor import Scope

        frozen_scope = Scope()
        for v in test_prog.global_block().vars.values():
            if getattr(v, "persistable", False) and scope.has(v.name):
                frozen_scope.set(v.name, scope.get(v.name))
        QuantizationFreezePass(
            scope=frozen_scope, weight_bits=self.weight_bits,
            activation_bits=self.activation_bits).apply(test_prog)
        context["quant_frozen_program"] = test_prog
        context["quant_frozen_scope"] = frozen_scope
        if self.int8_model_save_path and self.save_out_nodes:
            self._save(context, test_prog, frozen_scope,
                       self.int8_model_save_path)

    def _save(self, context, program, scope, path):
        from paddle_tpu import io as fluid_io
        from paddle_tpu.executor import scope_guard

        with scope_guard(scope):
            fluid_io.save_inference_model(
                path, list(self.save_in_nodes or []),
                [program.global_block().var(getattr(n, "name", n))
                 for n in self.save_out_nodes],
                context["exe"], main_program=program)
