from .quantization_pass import (  # noqa: F401
    QuantizationFreezePass,
    QuantizationTranspiler,
    TransformForTraining,
    QUANTIZABLE_OP_TYPES,
)
from .post_training import PostTrainingQuantization  # noqa: F401

__all__ = ["QuantizationFreezePass", "QuantizationTranspiler",
           "TransformForTraining", "QUANTIZABLE_OP_TYPES",
           "PostTrainingQuantization"]
