from .quantization_pass import (  # noqa: F401
    QuantizationTranspiler,
    TransformForTraining,
    QUANTIZABLE_OP_TYPES,
)

__all__ = ["QuantizationTranspiler", "TransformForTraining",
           "QUANTIZABLE_OP_TYPES"]
