"""Neural architecture search (reference ``contrib/slim/searcher/
controller.py`` EvolutionaryController/SAController +
``contrib/slim/nas/light_nas_strategy.py``).

TPU redesign: the reference's controller-server/agent RPC machinery
(controller_server.py, search_agent.py, lock.py) coordinated multi-
process trainers over sockets; here search runs in-process — each token
evaluation is one jit-compiled short training run, so the socket layer
has no role.  The controller API (reset/next_tokens/update) is kept
verbatim for strategy-porting parity."""

import math

import numpy as np

__all__ = ["EvolutionaryController", "SAController", "SearchSpace",
           "light_nas_search"]


class EvolutionaryController:
    """reference controller.py:28."""

    def update(self, tokens, reward):
        raise NotImplementedError

    def reset(self, range_table, constrain_func=None):
        raise NotImplementedError

    def next_tokens(self):
        raise NotImplementedError


class SAController(EvolutionaryController):
    """Simulated-annealing controller (reference controller.py:59)."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=0):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._reward = -1.0
        self._tokens = None
        self._max_reward = -1.0
        self._best_tokens = None
        self._iter = 0
        self._constrain_func = None
        self._rng = np.random.RandomState(seed)

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0

    def update(self, tokens, reward):
        """Accept better tokens always; worse ones with the annealing
        probability exp(dr / T) (reference controller.py:105)."""
        self._iter += 1
        temperature = self._init_temperature * (
            self._reduce_rate ** self._iter)
        dr = reward - self._reward
        if dr > 0 or self._rng.random_sample() <= math.exp(
                dr / max(temperature, 1e-9)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self):
        """Mutate one random position (reference controller.py:127)."""
        for _ in range(self._max_iter_number):
            new_tokens = list(self._tokens)
            index = int(self._rng.randint(len(self._range_table)))
            rt = self._range_table[index]
            new_tokens[index] = (
                new_tokens[index] + self._rng.randint(rt - 1) + 1) % rt
            if self._constrain_func is None \
                    or self._constrain_func(new_tokens):
                return new_tokens
        return list(self._tokens)


class SearchSpace:
    """reference nas/search_space.py: subclass and implement the three
    hooks; `create_net(tokens)` returns (startup, main, loss) or any
    structure your reward_fn understands."""

    def init_tokens(self):
        raise NotImplementedError

    def range_table(self):
        raise NotImplementedError

    def create_net(self, tokens):
        raise NotImplementedError


def light_nas_search(search_space, reward_fn, search_steps=50,
                     controller=None, constrain_func=None):
    """In-process LightNAS loop (reference light_nas_strategy.py
    on_compression_begin): anneal over the token space, evaluating each
    candidate with `reward_fn(net)`; returns (best_tokens, best_reward).

    ``constrain_func`` gates EVERY candidate including the initial
    tokens — an over-budget init seeds the mutation walk but is never
    evaluated or eligible as best."""
    ctl = controller or SAController()
    init = search_space.init_tokens()
    ctl.reset(search_space.range_table(), init, constrain_func)
    if constrain_func is None or constrain_func(init):
        reward = reward_fn(search_space.create_net(init))
        ctl.update(init, reward)
    for _ in range(search_steps):
        tokens = ctl.next_tokens()
        reward = reward_fn(search_space.create_net(tokens))
        ctl.update(tokens, reward)
    return ctl.best_tokens, ctl.max_reward
