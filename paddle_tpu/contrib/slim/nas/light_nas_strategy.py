"""LightNASStrategy (reference ``contrib/slim/nas/light_nas_strategy.py``:
run the SA-controller search at compression begin — each candidate
scored by a short train/eval — under a FLOPs constraint read off the
candidate's graph).

TPU redesign note: the reference delegates candidate evaluation to a
controller *server* + socket-connected search agents; here evaluation is
in-process (each candidate is one jit-compiled short run), so the
strategy is a thin loop over ``light_nas_search`` with the constraint
built from the slim GraphWrapper."""

from ..core import Strategy
from ..graph import GraphWrapper
from . import SAController, light_nas_search

__all__ = ["LightNASStrategy"]


class LightNASStrategy(Strategy):
    """Search at ``on_compression_begin``; stores ``best_tokens`` /
    ``best_reward`` in the context and on self.

    search_space: a ``SearchSpace`` (init_tokens/range_table/create_net).
    reward_fn: net -> float (higher is better), e.g. short-train the
        candidate and return -loss or eval accuracy.
    max_flops: optional FLOPs budget; candidates whose program exceeds
        it are never evaluated (the reference's flops constraint).
    program_of: net -> Program used for the FLOPs check; defaults to
        ``net[1]`` matching SearchSpace.create_net's documented
        (startup, main, loss) shape.
    """

    def __init__(self, search_space, reward_fn, search_steps=50,
                 max_flops=None, program_of=None, controller=None,
                 start_epoch=0, end_epoch=0):
        super().__init__(start_epoch, end_epoch)
        self.search_space = search_space
        self.reward_fn = reward_fn
        self.search_steps = search_steps
        self.max_flops = max_flops
        self.program_of = program_of or (lambda net: net[1])
        self.controller = controller or SAController()
        self.best_tokens = None
        self.best_reward = None

    def _constrain(self, tokens):
        if self.max_flops is None:
            return True
        net = self.search_space.create_net(tokens)
        return GraphWrapper(
            self.program_of(net)).flops() <= self.max_flops

    def on_compression_begin(self, context):
        constrain = (self._constrain if self.max_flops is not None
                     else None)
        tokens, reward = light_nas_search(
            self.search_space, self.reward_fn,
            search_steps=self.search_steps, controller=self.controller,
            constrain_func=constrain)
        self.best_tokens, self.best_reward = tokens, reward
        context["nas_best_tokens"] = tokens
        context["nas_best_reward"] = reward
