"""Graph wrappers (reference ``contrib/slim/graph/graph_wrapper.py``:
``VarWrapper``/``OpWrapper``/``GraphWrapper`` — the uniform view every
slim strategy uses to walk a Program, find producer/consumer ops, pull
parameters, and cost the model in FLOPs/params).

TPU note: the reference wraps ``IrGraph`` over the C++ graph; here the
same API wraps ``Program`` directly — the Program IS the graph (SSA
versioning is the executor's concern), so wrappers stay thin views and
every mutation routes through the normal Block APIs.
"""

import numpy as np

__all__ = ["VarWrapper", "OpWrapper", "GraphWrapper", "op_flops"]


class VarWrapper:
    """reference graph_wrapper.py:VarWrapper."""

    def __init__(self, var, graph):
        self._var = var
        self._graph = graph

    def name(self):
        return self._var.name

    def shape(self):
        return list(self._var.shape or ())

    def set_shape(self, shape):
        self._var.shape = tuple(shape)

    def is_parameter(self):
        return (type(self._var).__name__ == "Parameter"
                or getattr(self._var, "persistable", False))

    def inputs(self):
        """Ops that produce this var."""
        return [op for op in self._graph.ops()
                if self.name() in op.all_output_names()]

    def outputs(self):
        """Ops that consume this var."""
        return [op for op in self._graph.ops()
                if self.name() in op.all_input_names()]

    def __eq__(self, other):
        return isinstance(other, VarWrapper) and \
            self._var.name == other._var.name

    def __hash__(self):
        return hash(self._var.name)

    def __repr__(self):
        return "VarWrapper(%s%s)" % (self.name(), self.shape())


class OpWrapper:
    """reference graph_wrapper.py:OpWrapper."""

    def __init__(self, op, graph):
        self._op = op
        self._graph = graph

    def type(self):
        return self._op.type

    def idx(self):
        return self._graph._block.ops.index(self._op)

    def all_input_names(self):
        return [n for ns in self._op.inputs.values() for n in ns if n]

    def all_output_names(self):
        return [n for ns in self._op.outputs.values() for n in ns if n]

    def all_inputs(self):
        return [self._graph.var(n) for n in self.all_input_names()
                if self._graph.has_var(n)]

    def all_outputs(self):
        return [self._graph.var(n) for n in self.all_output_names()
                if self._graph.has_var(n)]

    def inputs(self, name):
        """Vars bound to input slot `name`."""
        return [self._graph.var(n) for n in self._op.inputs.get(name, [])
                if n and self._graph.has_var(n)]

    def outputs(self, name):
        return [self._graph.var(n) for n in self._op.outputs.get(name, [])
                if n and self._graph.has_var(n)]

    def attr(self, name):
        return self._op.attrs.get(name)

    def set_attr(self, name, value):
        self._op.attrs[name] = value

    def __eq__(self, other):
        return isinstance(other, OpWrapper) and self._op is other._op

    def __hash__(self):
        return id(self._op)

    def __repr__(self):
        return "OpWrapper(%s)" % self.type()


class GraphWrapper:
    """reference graph_wrapper.py:GraphWrapper — Program-level view with
    producer/consumer walks and model costing."""

    def __init__(self, program, in_nodes=None, out_nodes=None):
        self.program = program
        self._block = program.global_block()
        self.in_nodes = dict(in_nodes or {})
        self.out_nodes = dict(out_nodes or {})

    # -- structure ----------------------------------------------------

    def all_parameters(self):
        return [VarWrapper(v, self) for v in self._block.vars.values()
                if type(v).__name__ == "Parameter"
                or getattr(v, "persistable", False)]

    def is_parameter(self, var):
        return var.is_parameter()

    def ops(self):
        return [OpWrapper(op, self) for op in self._block.ops]

    def vars(self):
        return [VarWrapper(v, self) for v in self._block.vars.values()]

    def var(self, name):
        return VarWrapper(self._block._find_var_recursive(name), self)

    def has_var(self, name):
        return self._block._find_var_recursive(name) is not None

    def pre_ops(self, op):
        """Ops producing any input of `op` (reference pre_ops)."""
        ins = set(op.all_input_names())
        return [o for o in self.ops()
                if ins & set(o.all_output_names())]

    def next_ops(self, op):
        """Ops consuming any output of `op` (reference next_ops)."""
        outs = set(op.all_output_names())
        return [o for o in self.ops()
                if outs & set(o.all_input_names())]

    def get_param_by_op(self, op):
        """Parameters read by `op` (reference get_param_by_op)."""
        return [v for v in op.all_inputs() if v.is_parameter()]

    def clone(self, for_test=False):
        return GraphWrapper(self.program.clone(for_test=for_test),
                            self.in_nodes, self.out_nodes)

    # -- costing (reference graph_wrapper.py flops/numel_params) ------

    def numel_params(self):
        return int(sum(
            np.prod([d for d in p.shape() if d > 0]) or 0
            for p in self.all_parameters()))

    def flops(self):
        """Static FLOPs of the forward ops (reference flops(): conv,
        mul/matmul, pool, elementwise, relu counted; 2*MACs for the
        matmul-class ops)."""
        return int(sum(op_flops(op) for op in self.ops()))


def op_flops(op):
    """Per-op static FLOPs (shared by GraphWrapper.flops and
    contrib.model_stat.summary — the reference counts the same op set
    in both places)."""
    t = op.type()
    if t in ("conv2d", "depthwise_conv2d"):
        out = op.outputs("Output")
        flt = op.inputs("Filter")
        if not out or not flt:
            return 0
        oshape = out[0].shape()
        fshape = flt[0].shape()
        if len(oshape) < 4 or len(fshape) < 4:
            return 0
        # 2 * H_out*W_out * Cout * (Cin/g * kh * kw) per image
        total = int(2 * oshape[2] * oshape[3] * fshape[0]
                    * (fshape[1] * fshape[2] * fshape[3]))
        if op.inputs("Bias"):
            total += int(np.prod(oshape[1:]))
        return total
    if t in ("mul", "matmul"):
        x = op.inputs("X")
        y = op.inputs("Y")
        if not x or not y:
            return 0
        xs, ys = x[0].shape(), y[0].shape()
        if len(xs) >= 2 and len(ys) >= 2:
            m = int(np.prod([d for d in xs[:-1] if d > 0]) or 1)
            return 2 * m * xs[-1] * ys[-1]
        return 0
    if t in ("relu", "sigmoid", "tanh", "elementwise_add",
             "elementwise_mul", "batch_norm", "pool2d"):
        out = op.all_outputs()
        if out:
            return int(np.prod(
                [d for d in out[0].shape() if d > 0]) or 0)
    return 0
