"""slim Compressor (reference: ``contrib/slim/core/compressor.py:229``
— the strategy-driven compression driver: reads a YAML config naming
quantization/pruning/distillation strategies and runs epochs applying
them around a train/eval graph)."""

__all__ = ["Compressor"]


class Compressor:
    def __init__(self, place, scope, train_program, train_reader=None,
                 train_feed_list=None, train_fetch_list=None,
                 eval_program=None, eval_reader=None, eval_feed_list=None,
                 eval_fetch_list=None, teacher_programs=None,
                 checkpoint_path="./checkpoints", train_optimizer=None,
                 distiller_optimizer=None):
        self.place = place
        self.scope = scope
        self.train_program = train_program
        self.train_reader = train_reader
        self.train_feed_list = train_feed_list
        self.train_fetch_list = train_fetch_list
        self.eval_program = eval_program
        self.eval_reader = eval_reader
        self.eval_feed_list = eval_feed_list
        self.eval_fetch_list = eval_fetch_list
        self.checkpoint_path = checkpoint_path
        self.train_optimizer = train_optimizer
        self.epoch = 1
        self.strategies = []

    def config(self, config_file):
        """Load the strategy list.  The reference parses a YAML registry
        of strategy classes; here accept either a YAML path (parsed for
        the compress_pass epoch + strategies) or a plain list of strategy
        objects (each with on_epoch_begin/on_epoch_end hooks)."""
        if isinstance(config_file, (list, tuple)):
            self.strategies = list(config_file)
            return self
        import yaml  # the image ships pyyaml

        with open(config_file) as f:
            cfg = yaml.safe_load(f) or {}
        cp = cfg.get("compress_pass", cfg.get("compressor", {})) or {}
        self.epoch = int(cp.get("epoch", 1))
        self.strategies = cp.get("strategies", []) or []
        return self

    def run(self):
        """Run the configured epochs, invoking each strategy's hooks
        around the training loop (the compressor's driver role; the
        strategies themselves are the slim quant/prune/distill passes)."""
        from ...executor import Executor

        exe = Executor(self.place)
        feeder = None
        if self.train_feed_list:
            from ...data_feeder import DataFeeder

            feeder = DataFeeder(self.train_feed_list,
                                program=self.train_program)
        context = {"exe": exe, "program": self.train_program,
                   "scope": self.scope, "epoch": 0}
        for epoch in range(self.epoch):
            context["epoch"] = epoch
            for s in self.strategies:
                if hasattr(s, "on_epoch_begin"):
                    s.on_epoch_begin(context)
            if self.train_reader is not None:
                for batch in self.train_reader():
                    # reference contract: the reader yields sample-tuple
                    # batches converted through train_feed_list; a dict
                    # passes straight through
                    feed = (batch if isinstance(batch, dict)
                            else feeder.feed(batch) if feeder is not None
                            else None)
                    if feed is None:
                        raise ValueError(
                            "Compressor needs train_feed_list to convert "
                            "sample batches (or a reader yielding feed "
                            "dicts)")
                    exe.run(self.train_program, feed=feed,
                            fetch_list=self.train_fetch_list or [],
                            scope=self.scope)
            for s in self.strategies:
                if hasattr(s, "on_epoch_end"):
                    s.on_epoch_end(context)
        return context
