"""slim core: the Compressor driver + Strategy base (reference:
``contrib/slim/core/compressor.py:229`` and ``core/strategy.py`` — the
strategy-driven compression loop: strategies hook compression/epoch
boundaries, rewrite the training graph, and the compressor runs the
epochs around them)."""

__all__ = ["Compressor", "Strategy"]


def _resolve_strategy_class(name):
    """Strategy-class registry for YAML configs (the reference resolves
    class names through its factory the same way)."""
    from .distillation.distillation_strategy import DistillationStrategy
    from .prune.prune_strategy import (SensitivePruneStrategy,
                                       UniformPruneStrategy)
    from .quantization.quantization_strategy import QuantizationStrategy

    reg = {c.__name__: c for c in (
        UniformPruneStrategy, SensitivePruneStrategy,
        QuantizationStrategy, DistillationStrategy)}
    if name not in reg:
        raise ValueError("unknown strategy class %r (known: %s)"
                         % (name, sorted(reg)))
    return reg[name]


def _resolve_pruner_class(name):
    from .prune import MagnitudePruner, StructurePruner

    reg = {c.__name__: c for c in (StructurePruner, MagnitudePruner)}
    if name not in reg:
        raise ValueError("unknown pruner class %r (known: %s)"
                         % (name, sorted(reg)))
    return reg[name]


class Strategy:
    """reference ``core/strategy.py:Strategy``: hook points around the
    compression run and each epoch.  ``start_epoch``/``end_epoch``
    bound when a subclass acts (reference semantics: act on epoch
    boundaries within [start_epoch, end_epoch])."""

    def __init__(self, start_epoch=0, end_epoch=0):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compression_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_compression_end(self, context):
        pass


class Compressor:
    def __init__(self, place, scope, train_program, train_reader=None,
                 train_feed_list=None, train_fetch_list=None,
                 eval_program=None, eval_reader=None, eval_feed_list=None,
                 eval_fetch_list=None, teacher_programs=None,
                 checkpoint_path="./checkpoints", train_optimizer=None,
                 distiller_optimizer=None, startup_program=None):
        self.place = place
        self.scope = scope
        self.train_program = train_program
        self.startup_program = startup_program
        self.train_reader = train_reader
        self.train_feed_list = train_feed_list
        self.train_fetch_list = train_fetch_list
        self.eval_program = eval_program
        self.eval_reader = eval_reader
        self.eval_feed_list = eval_feed_list
        self.eval_fetch_list = eval_fetch_list
        self.checkpoint_path = checkpoint_path
        self.train_optimizer = train_optimizer
        self.distiller_optimizer = distiller_optimizer
        self.epoch = 1
        self.strategies = []

    def config(self, config_file):
        """Load the strategy list: either a plain list of strategy
        objects, or a YAML path in the reference's registry shape
        (``compressor.py _load_config``) —

            strategies:
              prune_one:
                class: UniformPruneStrategy
                target_ratio: 0.5
            pruners:
              pruner_1:
                class: StructurePruner
            compress_pass:
              epoch: 2
              strategies: [prune_one]

        ``class`` names resolve from the slim strategy/pruner registry;
        a strategy's ``pruner:`` kwarg may name an entry in the
        top-level ``pruners`` section."""
        if isinstance(config_file, (list, tuple)):
            self.strategies = list(config_file)
            return self
        import yaml  # the image ships pyyaml

        with open(config_file) as f:
            cfg = yaml.safe_load(f) or {}
        cp = cfg.get("compress_pass", cfg.get("compressor", {})) or {}
        self.epoch = int(cp.get("epoch", 1))
        named = cfg.get("strategies", {}) or {}
        pruners = cfg.get("pruners", {}) or {}
        out = []
        for entry in cp.get("strategies", []) or []:
            if isinstance(entry, str):
                spec = dict(named.get(entry) or {})
                if not spec:
                    raise ValueError(
                        "strategy %r not found in the top-level "
                        "'strategies' section" % entry)
            else:
                spec = dict(entry or {})
            if "class" not in spec:
                raise ValueError(
                    "strategy spec %r has no 'class' key" % (entry,))
            cls = _resolve_strategy_class(spec.pop("class"))
            if isinstance(spec.get("pruner"), str):
                pname = spec["pruner"]
                if pname not in pruners:
                    raise ValueError(
                        "pruner %r not found in the top-level 'pruners' "
                        "section (known: %s)" % (pname, sorted(pruners)))
                pspec = dict(pruners[pname] or {})
                pcls = _resolve_pruner_class(pspec.pop("class",
                                                       "StructurePruner"))
                spec["pruner"] = pcls(**pspec)
            out.append(cls(**spec))
        self.strategies = out
        return self

    def _maybe_minimize(self, context):
        """Build the optimizer into the (possibly strategy-rewritten)
        forward program — the reference compressor's _init_model role.
        Runs AFTER on_compression_begin so graph-rewriting strategies
        (QAT insertion) see the forward graph, exactly like the
        reference's graph-then-compile ordering.  No-op when the program
        already carries grad ops (caller pre-minimized)."""
        if self.train_optimizer is None or not self.train_fetch_list:
            return
        prog = context["program"]
        if any(op.type.endswith("_grad") for op in prog.global_block().ops):
            return
        from ...framework import Program, program_guard

        loss_name = self.train_fetch_list[0]
        loss_name = getattr(loss_name, "name", loss_name)
        loss = prog.global_block().var(loss_name)
        startup = context.get("startup_program")
        if startup is None:
            startup = Program()
            context["startup_program"] = startup
        with program_guard(prog, startup):
            self.train_optimizer.minimize(loss)

    def run(self):
        """Run the configured epochs, invoking each strategy's hooks
        around the training loop (the compressor's driver role; the
        strategies themselves are the slim quant/prune/distill passes)."""
        from ...executor import Executor

        exe = Executor(self.place)
        feeder = None
        if self.train_feed_list:
            from ...data_feeder import DataFeeder

            feeder = DataFeeder(self.train_feed_list,
                                program=self.train_program)
        context = {"exe": exe, "program": self.train_program,
                   "eval_program": self.eval_program,
                   "scope": self.scope, "epoch": 0,
                   "place": self.place,
                   "startup_program": self.startup_program,
                   "train_fetch_list": self.train_fetch_list,
                   "distiller_optimizer": self.distiller_optimizer,
                   "checkpoint_path": self.checkpoint_path}
        for s in self.strategies:
            if hasattr(s, "on_compression_begin"):
                s.on_compression_begin(context)
        self._maybe_minimize(context)
        # init AFTER strategies + minimize so strategy-added state
        # (quant scales) and optimizer accumulators exist (the reference
        # compressor's own init ordering); callers who pre-initialize or
        # load a checkpoint simply don't pass startup_program
        if context.get("startup_program") is not None:
            exe.run(context["startup_program"], scope=self.scope)
        for epoch in range(self.epoch):
            context["epoch"] = epoch
            for s in self.strategies:
                if hasattr(s, "on_epoch_begin"):
                    s.on_epoch_begin(context)
            if self.train_reader is not None:
                for batch in self.train_reader():
                    # reference contract: the reader yields sample-tuple
                    # batches converted through train_feed_list; a dict
                    # passes straight through
                    feed = (batch if isinstance(batch, dict)
                            else feeder.feed(batch) if feeder is not None
                            else None)
                    if feed is None:
                        raise ValueError(
                            "Compressor needs train_feed_list to convert "
                            "sample batches (or a reader yielding feed "
                            "dicts)")
                    exe.run(context["program"], feed=feed,
                            fetch_list=context.get("train_fetch_list")
                            or [],
                            scope=self.scope)
            for s in self.strategies:
                if hasattr(s, "on_epoch_end"):
                    s.on_epoch_end(context)
        for s in self.strategies:
            if hasattr(s, "on_compression_end"):
                s.on_compression_end(context)
        return context
