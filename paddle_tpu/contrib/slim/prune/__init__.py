"""Model pruning (reference ``contrib/slim/prune/pruner.py``
StructurePruner + prune_strategy.py sensitivity pruning).

TPU redesign: pruning is a SCOPE transform, not a graph pass — under XLA
the win from structured sparsity is realized by shrinking the actual
weight shapes at export; during sensitivity analysis the framework keeps
shapes static and applies mask-zeroing (so one compiled program serves
every ratio)."""

import numpy as np

__all__ = ["Pruner", "StructurePruner", "MagnitudePruner",
           "sensitivity_analysis"]


class Pruner:
    """Base pruner (reference pruner.py:Pruner)."""

    def prune(self, param):
        raise NotImplementedError


class StructurePruner(Pruner):
    """Group (filter/channel) pruning by l1 norm along an axis
    (reference pruner.py:StructurePruner)."""

    def __init__(self, pruning_axis=None, criterions=None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def _axis_for(self, name):
        return self.pruning_axis.get(name, self.pruning_axis.get("*", 0))

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        """Indices of the lowest-norm groups to prune (reference
        cal_pruned_idx)."""
        axis = self._axis_for(name) if axis is None else axis
        p = np.asarray(param)
        reduce_dims = tuple(i for i in range(p.ndim) if i != axis)
        norms = np.abs(p).sum(axis=reduce_dims)
        k = int(round(norms.shape[0] * float(ratio)))
        return np.argsort(norms)[:k]

    def prune_tensor(self, param, idx, axis, lazy=False):
        """Remove (or zero when lazy=True) the given groups (reference
        prune_tensor)."""
        p = np.asarray(param)
        if lazy:
            out = p.copy()
            sl = [slice(None)] * p.ndim
            sl[axis] = idx
            out[tuple(sl)] = 0.0
            return out
        return np.delete(p, idx, axis=axis)

    def prune_scope(self, scope, name, ratio, lazy=True):
        """Apply pruning to a parameter living in an executor scope."""
        val = np.asarray(scope.get(name))
        axis = self._axis_for(name)
        idx = self.cal_pruned_idx(name, val, ratio)
        scope.set(name, self.prune_tensor(val, idx, axis, lazy=lazy))
        return idx


class MagnitudePruner(Pruner):
    """Unstructured magnitude pruning: zero the smallest |w| entries."""

    def __init__(self, ratio):
        self.ratio = float(ratio)

    def prune(self, param):
        p = np.asarray(param)
        k = int(p.size * self.ratio)
        if k == 0:
            return p
        thresh = np.partition(np.abs(p).ravel(), k - 1)[k - 1]
        return np.where(np.abs(p) <= thresh, 0.0, p).astype(p.dtype)


def sensitivity_analysis(executor, program, feed, fetch_loss, scope,
                         param_names, ratios=(0.1, 0.3, 0.5), lazy=True):
    """Per-parameter pruning sensitivity (reference
    prune_strategy.py:SensitivePruneStrategy._compute_sensitivities):
    prune each param at each ratio, measure the loss delta on one batch,
    restore, and return {param: {ratio: loss}}."""
    pruner = StructurePruner()
    base = float(np.asarray(
        executor.run(program, feed=feed, fetch_list=[fetch_loss],
                     scope=scope)[0]).reshape(()))
    report = {}
    for name in param_names:
        saved = np.asarray(scope.get(name)).copy()
        report[name] = {0.0: base}
        for ratio in ratios:
            pruner.prune_scope(scope, name, ratio, lazy=lazy)
            loss = float(np.asarray(
                executor.run(program, feed=feed, fetch_list=[fetch_loss],
                             scope=scope)[0]).reshape(()))
            report[name][ratio] = loss
            scope.set(name, saved)
    return report
