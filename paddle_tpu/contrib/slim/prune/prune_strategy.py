"""Pruning strategies for the slim Compressor (reference
``contrib/slim/prune/prune_strategy.py``: ``UniformPruneStrategy`` and
``SensitivePruneStrategy`` — pick per-parameter ratios, prune through
the Pruner, report the FLOPs/params saved via the GraphWrapper)."""

import fnmatch

import numpy as np

from ..core import Strategy
from ..graph import GraphWrapper
from . import StructurePruner, sensitivity_analysis

__all__ = ["UniformPruneStrategy", "SensitivePruneStrategy"]


def _match_params(graph, patterns):
    names = []
    for p in graph.all_parameters():
        if any(fnmatch.fnmatch(p.name(), pat) for pat in patterns):
            names.append(p.name())
    return names


class UniformPruneStrategy(Strategy):
    """Prune every matched parameter at the same ratio at
    ``start_epoch`` (reference prune_strategy.py:UniformPruneStrategy).

    Lazy (mask-zero) pruning keeps shapes static so the already-compiled
    program keeps serving — the TPU translation of the reference's
    in-place shape shrink, which XLA would treat as a recompile anyway.
    The structural shrink happens at export via ``Pruner.prune_tensor``.
    """

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, pruned_params="*.w_0", metric_name=None):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner or StructurePruner()
        self.target_ratio = float(target_ratio)
        self.pruned_params = pruned_params
        self.pruned_idx = {}

    def on_epoch_begin(self, context):
        if context["epoch"] != self.start_epoch:
            return
        graph = GraphWrapper(context["program"])
        scope = context["scope"]
        before = graph.numel_params()
        for name in _match_params(graph, [self.pruned_params]):
            self.pruned_idx[name] = self.pruner.prune_scope(
                scope, name, self.target_ratio, lazy=True)
        context["pruned_params"] = dict(self.pruned_idx)
        context["params_before_prune"] = before


class SensitivePruneStrategy(Strategy):
    """Sensitivity-guided pruning (reference
    prune_strategy.py:SensitivePruneStrategy): measure each parameter's
    loss sensitivity, then assign LOWER ratios to sensitive parameters
    and higher to insensitive ones until the mean ratio hits
    ``target_ratio``, pruning at ``start_epoch``."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 delta_rate=0.2, target_ratio=0.5,
                 pruned_params="*.w_0", sensitivities_file=None,
                 eval_batch=None, loss_name=None):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner or StructurePruner()
        self.delta_rate = float(delta_rate)
        self.target_ratio = float(target_ratio)
        self.pruned_params = pruned_params
        self.sensitivities_file = sensitivities_file
        self.eval_batch = eval_batch
        self.loss_name = loss_name
        self.sensitivities = {}
        self.ratios = {}

    def _compute_ratios(self, sens):
        """Invert sensitivity into per-param ratios whose mean equals
        target_ratio: ratio_i ∝ 1/(1+loss_delta_i) (the reference's
        greedy variant normalized in one shot)."""
        deltas = {}
        for name, by_ratio in sens.items():
            base = by_ratio.get(0.0)
            probe = max(r for r in by_ratio if r > 0)
            deltas[name] = max(by_ratio[probe] - base, 0.0)
        inv = {n: 1.0 / (1.0 + d) for n, d in deltas.items()}
        mean_inv = sum(inv.values()) / len(inv)
        return {n: min(0.9, self.target_ratio * v / mean_inv)
                for n, v in inv.items()}

    def on_epoch_begin(self, context):
        if context["epoch"] != self.start_epoch:
            return
        graph = GraphWrapper(context["program"])
        scope = context["scope"]
        names = _match_params(graph, [self.pruned_params])
        if self.eval_batch is None or self.loss_name is None:
            raise ValueError(
                "SensitivePruneStrategy needs eval_batch (a feed dict) "
                "and loss_name to measure sensitivities")
        self.sensitivities = sensitivity_analysis(
            context["exe"], context.get("eval_program")
            or context["program"], self.eval_batch, self.loss_name,
            scope, names, ratios=(self.delta_rate,), lazy=True)
        if self.sensitivities_file:
            import json

            with open(self.sensitivities_file, "w") as f:
                json.dump(self.sensitivities, f, default=float)
        self.ratios = self._compute_ratios(self.sensitivities)
        for name, ratio in self.ratios.items():
            self.pruner.prune_scope(scope, name, ratio, lazy=True)
        context["pruned_ratios"] = dict(self.ratios)

    def on_epoch_end(self, context):
        if context["epoch"] != self.end_epoch:
            return
        # report sparsity actually achieved (reference logs the same)
        scope = context["scope"]
        zeros = total = 0
        for name in self.ratios:
            w = np.asarray(scope.get(name))
            zeros += int((w == 0).sum())
            total += w.size
        context["achieved_sparsity"] = zeros / max(total, 1)
