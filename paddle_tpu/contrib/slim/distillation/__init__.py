"""Knowledge distillation losses (reference
``contrib/slim/distillation/distiller.py``: L2Distiller, FSPDistiller,
SoftLabelDistiller — each appends its loss subgraph to the merged
student+teacher program).

TPU note: the 'merge graphs' machinery of the reference collapses to
building teacher and student in ONE program (the teacher branch under
stop_gradient); these helpers only append the loss ops."""

__all__ = ["L2Distiller", "FSPDistiller", "SoftLabelDistiller",
           "l2_loss", "fsp_loss", "soft_label_loss"]


def l2_loss(teacher_var, student_var):
    """mean((t - s)^2) (reference distiller.py L2DistillerPass.apply)."""
    import paddle_tpu as fluid

    t = fluid.layers.assign(teacher_var)
    t.stop_gradient = True
    return fluid.layers.reduce_mean(
        fluid.layers.square(fluid.layers.elementwise_sub(student_var, t)))


def fsp_loss(teacher_var1, teacher_var2, student_var1, student_var2):
    """mean((FSP_t - FSP_s)^2) over flow matrices (reference
    FSPDistillerPass; fsp op = fsp_op.cc)."""
    import paddle_tpu as fluid

    t = fluid.layers.fsp_matrix(teacher_var1, teacher_var2)
    t.stop_gradient = True
    s = fluid.layers.fsp_matrix(student_var1, student_var2)
    return fluid.layers.reduce_mean(
        fluid.layers.square(fluid.layers.elementwise_sub(s, t)))


def soft_label_loss(teacher_logits, student_logits,
                    teacher_temperature=2.0, student_temperature=2.0):
    """Cross entropy of softened student vs softened teacher
    (reference SoftLabelDistillerPass)."""
    import paddle_tpu as fluid

    t = fluid.layers.softmax(
        fluid.layers.scale(teacher_logits, 1.0 / teacher_temperature))
    t.stop_gradient = True
    s = fluid.layers.softmax(
        fluid.layers.scale(student_logits, 1.0 / student_temperature))
    return fluid.layers.reduce_mean(
        fluid.layers.cross_entropy(s, t, soft_label=True))


class L2Distiller:
    """reference distiller.py:25 — callable returning the loss var."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1.0):
        self.student = student_feature_map
        self.teacher = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, student_var, teacher_var):
        import paddle_tpu as fluid

        return fluid.layers.scale(
            l2_loss(teacher_var, student_var), self.weight)


class FSPDistiller:
    """reference distiller.py:101."""

    def __init__(self, student_pairs=None, teacher_pairs=None,
                 distillation_loss_weight=1.0):
        self.student_pairs = student_pairs or []
        self.teacher_pairs = teacher_pairs or []
        self.weight = distillation_loss_weight

    def distiller_loss(self, svars, tvars):
        import paddle_tpu as fluid

        losses = [
            fsp_loss(t1, t2, s1, s2)
            for (s1, s2), (t1, t2) in zip(svars, tvars)
        ]
        total = losses[0]
        for l in losses[1:]:
            total = fluid.layers.elementwise_add(total, l)
        return fluid.layers.scale(total, self.weight)


class SoftLabelDistiller:
    """reference distiller.py SoftLabelDistiller."""

    def __init__(self, student_temperature=2.0, teacher_temperature=2.0,
                 distillation_loss_weight=1.0):
        self.st = student_temperature
        self.tt = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, student_logits, teacher_logits):
        import paddle_tpu as fluid

        return fluid.layers.scale(
            soft_label_loss(teacher_logits, student_logits,
                            self.tt, self.st),
            self.weight)
