"""DistillationStrategy for the slim Compressor (reference
``contrib/slim/distillation/distillation_strategy.py``: between
``start_epoch`` and ``end_epoch`` the compressor trains the DISTILL
graph — student+teacher merged, distiller losses appended — then
returns to the plain student graph).

TPU note: the reference merges separate teacher/student programs and
compiles the merged graph here; on this framework teacher and student
are built in ONE program (teacher branch under stop_gradient — see
``distillation/__init__.py``), so the strategy's job reduces to swapping
which program the compressor steps."""

from ..core import Strategy

__all__ = ["DistillationStrategy"]


class DistillationStrategy(Strategy):
    def __init__(self, distillers=None, start_epoch=0, end_epoch=0,
                 distill_program=None, distill_fetch_list=None):
        super().__init__(start_epoch, end_epoch)
        self.distillers = distillers or []
        self.distill_program = distill_program
        self.distill_fetch_list = distill_fetch_list
        self._saved = None

    def on_epoch_begin(self, context):
        if context["epoch"] == self.start_epoch \
                and self.distill_program is not None:
            self._saved = (context["program"],
                           context.get("train_fetch_list"))
            self._ensure_optimized(context)
            context["program"] = self.distill_program
            if self.distill_fetch_list is not None:
                context["train_fetch_list"] = self.distill_fetch_list

    def _ensure_optimized(self, context):
        """Build the distiller optimizer into the distill program on
        first entry (the reference strategy compiles the distill graph
        with ``distiller_optimizer`` the same way) — otherwise the
        distillation epochs would be forward-only no-ops."""
        prog = self.distill_program
        if any(op.type.endswith("_grad")
               for op in prog.global_block().ops):
            return
        opt = context.get("distiller_optimizer")
        fetch = self.distill_fetch_list or []
        if opt is None or not fetch:
            raise ValueError(
                "DistillationStrategy needs the Compressor's "
                "distiller_optimizer and a distill_fetch_list whose "
                "first entry is the distillation loss (the distill "
                "program carries no optimizer ops)")
        from paddle_tpu.framework import Program, program_guard

        loss_name = getattr(fetch[0], "name", fetch[0])
        startup = Program()
        with program_guard(prog, startup):
            opt.minimize(prog.global_block().var(loss_name))
        context["exe"].run(startup, scope=context["scope"])

    def on_epoch_end(self, context):
        if context["epoch"] == self.end_epoch and self._saved is not None:
            context["program"], fetch = self._saved
            if fetch is not None:
                context["train_fetch_list"] = fetch
            self._saved = None
