"""Model-compression toolkit (reference:
``python/paddle/fluid/contrib/slim/``): quantization-aware training,
structured/magnitude pruning + sensitivity analysis, distillation losses
(L2/FSP/soft-label), and simulated-annealing NAS."""

from . import quantization  # noqa: F401
from . import prune  # noqa: F401
from . import distillation  # noqa: F401
from . import nas  # noqa: F401
