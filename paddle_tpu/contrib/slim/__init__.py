"""Model-compression toolkit (reference:
``python/paddle/fluid/contrib/slim/``).  Quantization-aware training lives
in ``quantization``; pruning/NAS/distillation strategies are composed from
the base framework (clip/regularizer/program surgery) as needed."""

from . import quantization  # noqa: F401
