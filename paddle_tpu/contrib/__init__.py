"""Contrib surface (reference: ``python/paddle/fluid/contrib/``):
mixed_precision AMP, slim (quant/prune/NAS), extend optimizers."""

from . import mixed_precision
from . import slim

__all__ = ["mixed_precision", "slim"]
