"""Contrib surface (reference: ``python/paddle/fluid/contrib/``):
mixed_precision AMP, slim (quant/prune/NAS), extend_optimizer
(decoupled weight decay), memory/op-frequency diagnostics, fused
layers.  Not ported: decoder/ (the beam_search_decoder DSL — its
capability lives in layers.beam_search + DynamicRNN), reader/ and
utils/ (PS-era ctr/hdfs plumbing subsumed by datasets + the sharded
table path)."""

from . import mixed_precision
from . import slim
from . import extend_optimizer
from . import layers
from .memory_usage_calc import memory_usage
from .op_frequence import op_freq_statistic
from . import model_stat
from .model_stat import summary
from .extend_optimizer import extend_with_decoupled_weight_decay
from .layers import (BasicGRUUnit, BasicLSTMUnit, basic_gru, basic_lstm,
                     fused_elemwise_activation)
from .slim.quantization.quantization_pass import (
    QuantizationTranspiler as QuantizeTranspiler)
from .slim.core import Compressor
from .utils import HDFSClient, multi_download, multi_upload
from .checkpoint_utils import (convert_dist_to_sparse_program,
                               load_persistables_for_increment,
                               load_persistables_for_inference)
from . import reader
from .reader import distributed_batch_reader
from . import decoder
from .decoder import (BeamSearchDecoder, InitState, StateCell,
                      TrainingDecoder)

__all__ = ["mixed_precision", "slim", "extend_optimizer", "layers",
           "memory_usage", "op_freq_statistic", "model_stat", "summary",
           "extend_with_decoupled_weight_decay",
           "BasicGRUUnit", "BasicLSTMUnit", "basic_gru", "basic_lstm",
           "fused_elemwise_activation", "QuantizeTranspiler",
           "Compressor", "HDFSClient", "multi_download", "multi_upload",
           "convert_dist_to_sparse_program",
           "load_persistables_for_increment",
           "load_persistables_for_inference", "reader",
           "distributed_batch_reader", "decoder",
           "InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]
