"""Dygraph gradient clipping (reference:
``python/paddle/fluid/dygraph_grad_clip.py`` GradClipByValue/Norm/
GlobalNorm).  Same math as the graph-path clip classes — the optimizer's
eager minimize(grad_clip=...) applies them via
``Optimizer._dygraph_clip_grads``."""

from .clip import (GradientClipByGlobalNorm, GradientClipByNorm,
                   GradientClipByValue)

__all__ = ["GradClipByValue", "GradClipByNorm", "GradClipByGlobalNorm"]


class GradClipByValue(GradientClipByValue):
    pass


class GradClipByNorm(GradientClipByNorm):
    pass


class GradClipByGlobalNorm(GradientClipByGlobalNorm):
    def __init__(self, max_global_norm):
        super().__init__(max_global_norm)
