"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: /root/reference, czhu15/Paddle ~v1.5).

Design (see SURVEY.md): a static-graph ``Program`` IR is built by a Python layers
DSL (mirroring ``python/paddle/fluid/framework.py``), but execution is TPU-native:
the Executor lowers a whole block to a single jaxpr and caches the ``jax.jit``
compilation, instead of interpreting ops one by one against a mutable Scope
(reference: ``paddle/fluid/framework/executor.cc:416``).  Autodiff is
program-level reverse mode (``append_backward``) like the reference's
``python/paddle/fluid/backward.py``, with per-op grad rules derived from the op's
own XLA lowering via ``jax.vjp``.  Multi-device/multi-host training uses GSPMD
(`jax.jit` over a ``jax.sharding.Mesh``) in place of the reference's
ParallelExecutor/NCCL op-handle machinery.
"""

def _configure_jax():
    """TPU-friendly jax defaults, set before first trace.

    - rbg PRNG: the default threefry generator is counter-based and slow on
      TPU (the dropout masks alone cost ~25% of a BERT step); rbg uses the
      hardware RNG path and is the jax-recommended choice for dropout-class
      randomness on TPU.
    """
    import jax

    try:
        jax.config.update("jax_default_prng_impl", "rbg")
    except Exception:
        pass  # older/newer jax without the option — keep defaults


_configure_jax()

from . import core
from . import average
from . import analysis
from . import trainer_desc
from . import device_worker
from . import evaluator
from .framework import (
    Program,
    Block,
    Operator,
    Variable,
    Parameter,
    program_guard,
    name_scope,
    default_main_program,
    default_startup_program,
    switch_main_program,
    switch_startup_program,
    cpu_places,
    cuda_places,
    tpu_places,
    device_places,
    in_dygraph_mode,
)
from .executor import Executor, global_scope, scope_guard, Scope
from .param_attr import ParamAttr, WeightNormParamAttr
from .data_feeder import DataFeeder
from .core import CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace, set_flags, get_flags
from .backward import append_backward, gradients
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import layers
from . import initializer
from . import optimizer
from . import regularizer
from . import clip
from . import nets
from . import metrics
from . import io
from . import unique_name
from . import dygraph
from . import profiler
from . import contrib
from . import pipeline
from . import reader
from . import native
from . import recordio_writer
from . import inference
from . import reader_decorators
from . import dygraph_grad_clip
from . import install_check
from . import host_table
from . import autotune
from .lod_tensor import (LoDTensor, LoDTensorArray, create_lod_tensor,
                         create_random_int_lodtensor)
from .transpiler import memory_optimize, release_memory
from . import datasets
from .reader_decorators import batch
from .reader import PyReader, DataLoader
from .io import (
    save_vars,
    save_params,
    save_persistables,
    load_vars,
    load_params,
    load_persistables,
    save_inference_model,
    load_inference_model,
)
from .initializer import set_global_initializer  # noqa: F401
from .clip import GradientClipByGlobalNorm, GradientClipByNorm, GradientClipByValue
from .parallel import ParallelExecutor
from .dygraph.base import enable_dygraph, disable_dygraph
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from .data_feed_desc import DataFeedDesc
from .dataset import DatasetFactory
from . import static_analysis
from .static_analysis import analyze_program, verify_program
from . import resilience

# `import paddle_tpu as fluid` is the intended spelling for users of the
# reference's `import paddle.fluid as fluid`.
fluid = __import__(__name__)

__version__ = "0.1.0"

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "program_guard",
    "name_scope",
    "default_main_program",
    "default_startup_program",
    "Executor",
    "ParallelExecutor",
    "CompiledProgram",
    "BuildStrategy",
    "ExecutionStrategy",
    "global_scope",
    "scope_guard",
    "Scope",
    "ParamAttr",
    "WeightNormParamAttr",
    "DataFeeder",
    "CPUPlace",
    "TPUPlace",
    "CUDAPlace",
    "CUDAPinnedPlace",
    "append_backward",
    "gradients",
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "DataFeedDesc",
    "DatasetFactory",
    "layers",
    "initializer",
    "optimizer",
    "regularizer",
    "clip",
    "nets",
    "metrics",
    "io",
    "reader",
    "pipeline",
    "PyReader",
    "DataLoader",
    "unique_name",
    "dygraph",
    "profiler",
    "contrib",
    "cpu_places",
    "cuda_places",
    "tpu_places",
    "dygraph_grad_clip",
    "install_check",
    "in_dygraph_mode",
    "host_table",
    "autotune",
    "LoDTensor",
    "LoDTensorArray",
    "create_lod_tensor",
    "create_random_int_lodtensor",
    "memory_optimize",
    "release_memory",
    "is_compiled_with_cuda",
    "cuda_pinned_places",
    "static_analysis",
    "verify_program",
    "analyze_program",
    "resilience",
]


def is_compiled_with_cuda():
    """reference fluid.is_compiled_with_cuda — this backend is XLA/TPU."""
    return False


def cuda_pinned_places(device_count=None):
    """reference fluid.cuda_pinned_places: pinned host staging areas are
    XLA's job on TPU; returns CPU places for API compatibility."""
    return cpu_places(device_count)
