"""Composed nets (reference: ``python/paddle/fluid/nets.py``)."""

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "glu",
           "scaled_dot_product_attention", "sequence_conv_pool"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act,
    )
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling,
    )


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True,
                   data_format="NCHW"):
    tmp = input
    if not isinstance(conv_padding, list):
        conv_padding = [conv_padding] * len(conv_num_filter)
    if not isinstance(conv_filter_size, list):
        conv_filter_size = [conv_filter_size] * len(conv_num_filter)
    if not isinstance(conv_with_batchnorm, list):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if not isinstance(conv_batchnorm_drop_rate, list):
        conv_batchnorm_drop_rate = (
            [conv_batchnorm_drop_rate] * len(conv_num_filter)
        )
    for i in range(len(conv_num_filter)):
        local_act = conv_act if not conv_with_batchnorm[i] else None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i], padding=conv_padding[i],
            param_attr=param_attr, act=local_act,
            data_format=data_format,
        )
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act,
                                    data_layout=data_format)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp, conv_batchnorm_drop_rate[i])
    return layers.pool2d(
        input=tmp, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, data_format=data_format,
    )


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    from .layers import ops

    return layers.elementwise_mul(a, ops.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention (reference nets.py:503).
    All matmuls are MXU-shaped batched GEMMs."""
    d_key = queries.shape[-1] // num_heads

    def _split_heads(x):
        b, t, d = x.shape[0], x.shape[1], x.shape[2]
        x = layers.reshape(x, [0, 0, num_heads, d // num_heads])
        return layers.transpose(x, [0, 2, 1, 3])

    def _merge_heads(x):
        x = layers.transpose(x, [0, 2, 1, 3])
        return layers.reshape(x, [0, 0, x.shape[2] * x.shape[3]])

    q = _split_heads(queries)
    k = _split_heads(keys)
    v = _split_heads(values)
    scores = layers.matmul(q, k, transpose_y=True, alpha=d_key ** -0.5)
    weights = layers.softmax(scores)
    if dropout_rate:
        weights = layers.dropout(
            weights, dropout_prob=dropout_rate,
            dropout_implementation="upscale_in_train",
        )
    ctx = layers.matmul(weights, v)
    return _merge_heads(ctx)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       seq_len=None):
    """Sequence conv + pool composite (reference nets.py:249): input is a
    padded [B, T, N] batch (+ optional seq_len, the LoD replacement)."""
    conv = layers.sequence_conv(
        input, num_filters=num_filters, filter_size=filter_size,
        param_attr=param_attr, act=act, bias_attr=bias_attr,
        seq_len=seq_len)
    return layers.sequence_pool(conv, pool_type, seq_len=seq_len)
