"""Atomic + versioned checkpoints with integrity manifests and
auto-resume.

Layered over ``io.save_persistables``/``load_persistables`` (reference:
``fluid.io`` checkpoint_utils role).  Layout under a checkpoint root::

    <root>/
      ckpt-00000007/
        MANIFEST.json      # written LAST: schema, step, per-file sha256
        state.json         # trainer state: step counter, user extras
        vars/              # persistables (one .npy / .shards dir per var)
      ckpt-00000008/
      .tmp-00000009-<pid>/ # in-flight save (invisible to load)

Guarantees:

* **atomic**: everything is staged into a ``.tmp-*`` sibling and renamed
  into place in one ``os.rename``; a crash mid-save leaves only a tmp
  dir that loaders never look at (and the next save sweeps);
* **verified**: ``MANIFEST.json`` records a sha256 + size per file and is
  itself written last — a version missing its manifest, missing a listed
  file, or failing a checksum is *torn* and is skipped, never loaded;
* **versioned**: ``retain`` newest versions are kept (default env
  ``PADDLE_TPU_CKPT_RETAIN`` = 5), older ones pruned after a successful
  save — never before, so a failed save cannot eat the last good state;
* **retried**: the save/load bodies run under
  :func:`~paddle_tpu.resilience.retry.retry_call`, absorbing transient
  I/O failures (injected ``ckpt_write_fail``/``ckpt_read_fail`` faults
  included);
* **resumable**: :func:`try_load_latest_checkpoint` walks versions
  newest-first, loads the first intact one into the scope and returns
  its step + trainer state (``None`` when nothing valid exists — a fresh
  run, not an error).
"""

import collections
import hashlib
import json
import os
import shutil
import time
import warnings

from . import faults as _faults
from . import retry as _retry

__all__ = ["CheckpointInfo", "CorruptCheckpointError",
           "TopologyMismatchError", "save_checkpoint",
           "try_load_latest_checkpoint", "list_checkpoints",
           "verify_checkpoint", "read_topology", "MANIFEST_NAME",
           "CKPT_PREFIX"]

MANIFEST_NAME = "MANIFEST.json"
STATE_NAME = "state.json"
VARS_SUBDIR = "vars"
CKPT_PREFIX = "ckpt-"
_SCHEMA = 1

CheckpointInfo = collections.namedtuple(
    "CheckpointInfo", ["step", "path", "state"])


class CorruptCheckpointError(RuntimeError):
    """A checkpoint version failed integrity verification."""


class TopologyMismatchError(RuntimeError):
    """The manifest's recorded cluster topology (world size, ZeRO-1
    partitioning) does not match the cluster trying to restore from it.

    Deliberately NOT a :class:`CorruptCheckpointError`: the data is
    intact, it is just laid out for a different world — skipping the
    version (the corrupt-checkpoint policy) would silently restart
    training from an older topology-matching version or from scratch.
    The elastic recovery path catches this error and routes the version
    through :mod:`~paddle_tpu.resilience.reshard` instead."""

    def __init__(self, message, path=None, step=None, recorded=None,
                 expected=None):
        super().__init__(message)
        self.path = path
        self.step = step
        self.recorded = dict(recorded or {})
        self.expected = dict(expected or {})


def _default_retain():
    try:
        return int(os.environ.get("PADDLE_TPU_CKPT_RETAIN", "5"))
    except ValueError:
        return 5


def _file_sha256(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _walk_files(root):
    for dirpath, _, filenames in os.walk(root):
        for fname in sorted(filenames):
            full = os.path.join(dirpath, fname)
            yield os.path.relpath(full, root), full


def _version_dir(root, step):
    return os.path.join(root, "%s%08d" % (CKPT_PREFIX, int(step)))


def _parse_step(dirname):
    base = os.path.basename(dirname.rstrip(os.sep))
    if not base.startswith(CKPT_PREFIX):
        return None
    try:
        return int(base[len(CKPT_PREFIX):])
    except ValueError:
        return None


def list_checkpoints(root, include_torn=False):
    """``[(step, path)]`` of complete versions, newest first.  A version
    dir without a manifest is torn (the manifest is written last) and is
    excluded unless ``include_torn`` — torn dirs must count neither
    toward retention nor as "latest" anywhere; per-file integrity is
    verified at load."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        path = os.path.join(root, name)
        step = _parse_step(name)
        if step is None or not os.path.isdir(path):
            continue
        if not include_torn \
                and not os.path.exists(os.path.join(path, MANIFEST_NAME)):
            continue
        out.append((step, path))
    out.sort(key=lambda sp: sp[0], reverse=True)
    return out


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        pass  # EPERM etc.: exists but not ours — treat as alive
    return True


def _sweep_tmp(root):
    """Remove crashed saves' staging dirs (best-effort).  Only dirs
    whose owning pid is gone (or is us) are swept — a concurrent
    ``all_ranks`` saver's in-flight staging must not be deleted from
    under it."""
    if not os.path.isdir(root):
        return
    for name in os.listdir(root):
        if not (name.startswith(".tmp-") or name.startswith(".old-")):
            continue
        try:
            owner = int(name.rsplit("-", 1)[1])
        except (ValueError, IndexError):
            owner = None
        if owner is None or owner == os.getpid() \
                or not _pid_alive(owner):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def _is_primary():
    """Only one process of a cluster writes the shared checkpoint dirs
    (replicated persistables are identical everywhere; per-process shard
    files remain a single-host affair in this harness)."""
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0")) == 0
    except ValueError:
        return True


def save_checkpoint(executor, root, main_program=None, step=0, state=None,
                    retain=None, policy=None, all_ranks=False,
                    topology=None):
    """Write one atomic, verified checkpoint version; returns its final
    path (``None`` on non-primary cluster ranks unless ``all_ranks``).

    The whole body — stage, checksum, finalize — is one retryable unit:
    a transient failure anywhere discards the staging dir and starts
    over, so no partial version ever becomes visible.

    ``topology`` (a dict, e.g. ``{"world": 4, "zero1": False}``) is
    recorded in the manifest so a later restore on a DIFFERENT cluster
    shape is rejected with :class:`TopologyMismatchError` instead of
    silently loading misshapen shards (pass the matching
    ``expected_topology`` to :func:`try_load_latest_checkpoint`).
    """
    if not all_ranks and not _is_primary():
        return None
    from .. import io as fluid_io

    step = int(step)
    os.makedirs(root, exist_ok=True)
    _sweep_tmp(root)
    inj = _faults.get_injector()

    def _attempt():
        tmp = os.path.join(root, ".tmp-%08d-%d" % (step, os.getpid()))
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        try:
            vars_dir = os.path.join(tmp, VARS_SUBDIR)
            fluid_io.save_persistables(executor, vars_dir,
                                       main_program=main_program)
            with open(os.path.join(tmp, STATE_NAME), "w") as f:
                json.dump({"step": step, "state": state or {}}, f)
            # the injected transient fires AFTER the expensive writes so
            # a retry exercises the full stage-again path
            inj.maybe_fire("ckpt_write")
            files = {}
            for rel, full in _walk_files(tmp):
                files[rel] = {"sha256": _file_sha256(full),
                              "size": os.path.getsize(full)}
            manifest = {"schema": _SCHEMA, "step": step,
                        "wall_time": time.time(), "files": files}
            if topology:
                manifest["topology"] = dict(topology)
            from .atomic import atomic_write

            atomic_write(os.path.join(tmp, MANIFEST_NAME),
                         lambda f: json.dump(manifest, f, indent=1),
                         text=True)
            final = _version_dir(root, step)
            aside = None
            if os.path.isdir(final):
                # re-save of the same step: move the old version aside
                # FIRST (rename, not rmtree — the window between the two
                # renames is the narrowest possible; the old data is
                # never destroyed before the new version is in place)
                aside = os.path.join(
                    root, ".old-%08d-%d" % (step, os.getpid()))
                shutil.rmtree(aside, ignore_errors=True)
                os.rename(final, aside)
            os.rename(tmp, final)
            if aside is not None:
                shutil.rmtree(aside, ignore_errors=True)
            return final
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    t0 = time.perf_counter()
    final = _retry.retry_call(_attempt, policy=policy,
                              site="save_checkpoint(step=%d)" % step)
    from ..observability import runtime as _obs

    try:
        nbytes = sum(os.path.getsize(full)
                     for _rel, full in _walk_files(final))
    except OSError:
        nbytes = 0
    _obs.record_checkpoint_save(
        step, (time.perf_counter() - t0) * 1000.0, nbytes, final)
    _prune(root, retain if retain is not None else _default_retain())
    return final


def _prune(root, retain):
    if retain is None or retain <= 0:
        return
    complete = list_checkpoints(root)
    for _, path in complete[retain:]:
        shutil.rmtree(path, ignore_errors=True)
    # torn versions (no manifest — a crashed finalize from an older
    # writer, or tampering) are garbage: they can never be loaded, so
    # they must not accumulate either
    keep = {p for _, p in complete}
    for _, path in list_checkpoints(root, include_torn=True):
        if path not in keep:
            shutil.rmtree(path, ignore_errors=True)


def verify_checkpoint(path):
    """Integrity-check one version dir; returns its manifest dict or
    raises :class:`CorruptCheckpointError` naming what's wrong."""
    man_path = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(man_path):
        raise CorruptCheckpointError(
            "checkpoint %r has no %s (torn or in-flight save)"
            % (path, MANIFEST_NAME))
    try:
        with open(man_path) as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        raise CorruptCheckpointError(
            "checkpoint %r manifest unreadable: %s" % (path, e)) from e
    files = manifest.get("files")
    if not isinstance(files, dict):
        raise CorruptCheckpointError(
            "checkpoint %r manifest has no file table" % path)
    for rel, meta in files.items():
        full = os.path.join(path, rel)
        if not os.path.exists(full):
            raise CorruptCheckpointError(
                "checkpoint %r is missing file %r listed in its manifest"
                % (path, rel))
        size = os.path.getsize(full)
        if size != meta.get("size"):
            raise CorruptCheckpointError(
                "checkpoint %r file %r size %d != manifest %s (truncated "
                "write?)" % (path, rel, size, meta.get("size")))
        digest = _file_sha256(full)
        if digest != meta.get("sha256"):
            raise CorruptCheckpointError(
                "checkpoint %r file %r checksum mismatch (corrupt data)"
                % (path, rel))
    return manifest


def read_topology(path):
    """The cluster topology dict a version dir's manifest records, or
    ``None`` for legacy manifests saved before topology stamping."""
    man_path = os.path.join(path, MANIFEST_NAME)
    try:
        with open(man_path) as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        raise CorruptCheckpointError(
            "checkpoint %r manifest unreadable: %s" % (path, e)) from e
    topo = manifest.get("topology")
    return dict(topo) if isinstance(topo, dict) else None


def _check_topology(path, manifest, expected):
    """Raise :class:`TopologyMismatchError` when the manifest records a
    topology and any key present on BOTH sides disagrees.  Legacy
    manifests (no topology) and keys only one side knows are accepted —
    the check must never reject a checkpoint the old code would have
    loaded correctly."""
    if not expected:
        return
    recorded = manifest.get("topology")
    if not isinstance(recorded, dict):
        return
    diffs = {k: (recorded[k], expected[k]) for k in expected
             if k in recorded and recorded[k] != expected[k]}
    if diffs:
        raise TopologyMismatchError(
            "checkpoint %r was saved for a different cluster topology "
            "(%s); refusing to load misshapen shards — reshard it with "
            "resilience.reshard.reshard_checkpoint or restore at the "
            "recorded world size" % (
                path,
                ", ".join("%s: recorded %r != expected %r" % (k, r, e)
                          for k, (r, e) in sorted(diffs.items()))),
            path=path, step=manifest.get("step"),
            recorded=recorded, expected=expected)


def try_load_latest_checkpoint(executor, root, main_program=None,
                               policy=None, expected_topology=None):
    """Auto-resume: load the newest *intact* checkpoint version into the
    scope.  Corrupt/partial versions are warned about and skipped —
    exactly the torn-file scenario this layer exists for.  Returns a
    :class:`CheckpointInfo` (step, path, trainer state) or ``None`` when
    no loadable version exists.

    With ``expected_topology``, a version whose manifest records a
    conflicting topology raises :class:`TopologyMismatchError`
    immediately (no retry, no skip-to-older-version): the data is fine,
    the *world* changed, and silently loading misshapen shards — or
    quietly falling back to an older matching version — would corrupt
    the run.  The elastic path catches it and reshards."""
    from .. import io as fluid_io

    inj = _faults.get_injector()
    t0 = time.perf_counter()
    for step, path in list_checkpoints(root):
        try:
            def _attempt():
                inj.maybe_fire("ckpt_read")
                manifest = verify_checkpoint(path)
                _check_topology(path, manifest, expected_topology)
                fluid_io.load_persistables(
                    executor, os.path.join(path, VARS_SUBDIR),
                    main_program=main_program)
                return manifest

            manifest = _retry.retry_call(
                _attempt, policy=policy,
                site="load_checkpoint(%s)" % os.path.basename(path))
        except (CorruptCheckpointError, _retry.RetryExhaustedError) as e:
            # ONLY integrity/transient failures demote to skip-this-
            # version; anything else (model/checkpoint mismatch, a
            # systemic path problem) would recur on every version and
            # must fail fast, not silently restart training from step 0
            warnings.warn(
                "skipping unusable checkpoint %r: %s" % (path, e),
                RuntimeWarning, stacklevel=2)
            continue
        state = {}
        state_path = os.path.join(path, STATE_NAME)
        if os.path.exists(state_path):
            with open(state_path) as f:
                state = json.load(f).get("state", {})
        from ..observability import runtime as _obs

        _obs.record_checkpoint_load(
            manifest.get("step", step),
            (time.perf_counter() - t0) * 1000.0, path)
        return CheckpointInfo(step=manifest.get("step", step), path=path,
                              state=state)
    return None
