"""SLO-driven autoscaling: a control loop over the elastic membership
machinery and the serving decode tenants.

The loop is deliberately split into a *pure* decision function and a
thin actuator so every verdict is testable without a fleet:

- :class:`SLOPolicy` holds the declarative targets (p99 step latency,
  p99 serving latency, queue occupancy, shed rate, drift) plus the
  stability knobs — hysteresis band, idle low-watermark, cooldown, and
  min/max world/slot clamps.  ``decide()`` maps one monitor-collected
  status dict to a :class:`Decision` (grow / shrink / replan / no-op)
  with the evidence it was decided on.
- :class:`Autoscaler` runs collect → decide → journal → execute.  Every
  decision — including no-ops — is journaled (kind ``autoscale``) with
  its evidence and counted in ``autoscale_decisions_total{action=}``.
  Execution is delegated: growing launches a joiner through the
  caller's ``launch_worker`` (the new worker then runs the
  :mod:`.elastic` join protocol: join-request → admit → warm-up →
  ``member-<epoch+1>``), shrinking releases one through
  ``release_worker`` (a released worker exits and the fleet's
  scale-down path shrinks the membership), and serving capacity scales
  in place via ``DecodeEngine.resize``.

Hysteresis contract: a signal must exceed ``target * (1 + hysteresis)``
before the loop grows, and *every* monitored signal must sit below
``target * low_watermark`` (with an empty queue and zero shed) before
it shrinks — a value merely above target is in-band and yields a no-op,
so the fleet never flaps across the target line.

``PADDLE_TPU_AUTOSCALE=0`` is the master kill switch: the loop still
reports what it *would* observe but decides ``no-op`` and never
actuates, and constructing a trainer without any :class:`SLOPolicy`
leaves the scale-down-only behavior untouched.
"""

import collections
import os
import threading
import time

__all__ = [
    "GROW", "SHRINK", "REPLAN", "NOOP",
    "Decision", "SLOPolicy", "Autoscaler", "autoscale_enabled",
]

GROW = "grow"
SHRINK = "shrink"
REPLAN = "replan"
NOOP = "no-op"

Decision = collections.namedtuple(
    "Decision", ["action", "reason", "world", "target_world",
                 "slots", "target_slots", "evidence"])


def autoscale_enabled():
    """Master kill switch: ``PADDLE_TPU_AUTOSCALE=0`` forces every
    decision to no-op and disables actuation."""
    return os.environ.get("PADDLE_TPU_AUTOSCALE", "1") \
        .strip().lower() not in ("0", "false", "off")


class SLOPolicy:
    """Declarative SLO targets with the stability knobs that keep an
    autoscaler from flapping.

    A ``None`` target removes that signal from consideration.  Signals
    are read from a flat monitor-style status dict: ``p99_step_ms``,
    ``p99_serving_latency_ms``, ``serving_queue_depth``,
    ``serving_shed_rate``, and ``drift`` (worst per-var ratio).
    """

    def __init__(self, min_world=1, max_world=8, p99_step_ms=None,
                 p99_latency_ms=None, queue_depth=None, shed_rate=0.0,
                 drift_ratio=None, hysteresis=0.2, low_watermark=0.5,
                 cooldown_s=60.0, grow_step=1, shrink_step=1,
                 min_slots=1, max_slots=8):
        if int(min_world) < 1 or int(max_world) < int(min_world):
            raise ValueError(
                "world bounds must satisfy 1 <= min_world <= max_world,"
                " got [%s, %s]" % (min_world, max_world))
        if int(min_slots) < 1 or int(max_slots) < int(min_slots):
            raise ValueError(
                "slot bounds must satisfy 1 <= min_slots <= max_slots,"
                " got [%s, %s]" % (min_slots, max_slots))
        if float(hysteresis) < 0:
            raise ValueError("hysteresis must be >= 0")
        if not 0.0 < float(low_watermark) < 1.0:
            raise ValueError("low_watermark must be in (0, 1)")
        self.min_world = int(min_world)
        self.max_world = int(max_world)
        self.p99_step_ms = p99_step_ms
        self.p99_latency_ms = p99_latency_ms
        self.queue_depth = queue_depth
        self.shed_rate = shed_rate
        self.drift_ratio = drift_ratio
        self.hysteresis = float(hysteresis)
        self.low_watermark = float(low_watermark)
        self.cooldown_s = float(cooldown_s)
        self.grow_step = max(int(grow_step), 1)
        self.shrink_step = max(int(shrink_step), 1)
        self.min_slots = int(min_slots)
        self.max_slots = int(max_slots)

    def _targets(self):
        return (("p99_step_ms", self.p99_step_ms),
                ("p99_serving_latency_ms", self.p99_latency_ms),
                ("serving_queue_depth", self.queue_depth))

    def decide(self, status, world, now=None, last_action_ts=None,
               slots=None):
        """Map one status observation to a :class:`Decision`.

        Pure: no clocks beyond the passed ``now``, no I/O — the bench
        decision gate and the tests drive it with synthetic statuses.
        """
        now = time.time() if now is None else now
        status = status or {}
        world = int(world)
        evidence = {}
        breaches = []
        below_watermark = []
        observed = 0
        for field, target in self._targets():
            if target is None:
                continue
            value = status.get(field)
            if value is None:
                continue
            value = float(value)
            observed += 1
            evidence[field] = value
            if value > float(target) * (1.0 + self.hysteresis):
                breaches.append("%s=%.4g > %.4g (target %.4g +%d%%)"
                                % (field, value,
                                   float(target) * (1 + self.hysteresis),
                                   float(target),
                                   round(self.hysteresis * 100)))
            elif value <= float(target) * self.low_watermark:
                below_watermark.append(field)
        shed = status.get("serving_shed_rate")
        if self.shed_rate is not None and shed is not None:
            shed = float(shed)
            evidence["serving_shed_rate"] = shed
            if shed > float(self.shed_rate):
                breaches.append("serving_shed_rate=%.4g > %.4g"
                                % (shed, float(self.shed_rate)))
        drift = status.get("drift")
        if isinstance(drift, dict):
            drift = max([v for v in drift.values()
                         if isinstance(v, (int, float))] or [None])
        if self.drift_ratio is not None and drift is not None:
            drift = float(drift)
            evidence["drift"] = drift

        def _decision(action, reason, target_world=None,
                      target_slots=None):
            return Decision(action=action, reason=reason, world=world,
                            target_world=target_world
                            if target_world is not None else world,
                            slots=slots, target_slots=target_slots
                            if target_slots is not None else slots,
                            evidence=dict(evidence))

        if self.drift_ratio is not None and drift is not None \
                and drift > float(self.drift_ratio):
            return _decision(
                REPLAN, "drift %.4g exceeds ratio %.4g: the placement "
                "no longer matches the workload" % (
                    drift, float(self.drift_ratio)))

        in_cooldown = (last_action_ts is not None
                       and now - float(last_action_ts)
                       < self.cooldown_s)
        if breaches:
            if in_cooldown:
                return _decision(
                    NOOP, "overloaded (%s) but cooling down: %.0fs of "
                    "%.0fs elapsed" % ("; ".join(breaches),
                                       now - float(last_action_ts),
                                       self.cooldown_s))
            target_world = min(world + self.grow_step, self.max_world)
            target_slots = None
            if slots is not None:
                target_slots = min(int(slots) + 1, self.max_slots)
            if target_world == world and target_slots in (None, slots):
                return _decision(
                    NOOP, "overloaded (%s) but already at max_world=%d"
                    % ("; ".join(breaches), self.max_world))
            return _decision(GROW, "; ".join(breaches),
                             target_world=target_world,
                             target_slots=target_slots)

        queue_idle = float(status.get("serving_queue_depth") or 0) == 0
        shed_idle = float(status.get("serving_shed_rate") or 0) == 0
        idle = (observed > 0
                and len(below_watermark) == observed
                and queue_idle and shed_idle)
        if idle:
            if in_cooldown:
                return _decision(
                    NOOP, "idle (%s below %d%% watermark) but cooling "
                    "down" % (", ".join(below_watermark),
                              round(self.low_watermark * 100)))
            target_world = max(world - self.shrink_step,
                               self.min_world)
            target_slots = None
            if slots is not None:
                target_slots = max(int(slots) - 1, self.min_slots)
            if target_world == world and target_slots in (None, slots):
                return _decision(
                    NOOP, "idle but already at min_world=%d"
                    % self.min_world)
            return _decision(
                SHRINK, "%s below %d%% watermark, queue empty, no shed"
                % (", ".join(below_watermark),
                   round(self.low_watermark * 100)),
                target_world=target_world, target_slots=target_slots)
        return _decision(
            NOOP, "within band: no target breached beyond +%d%% "
            "hysteresis, not all signals idle"
            % round(self.hysteresis * 100))


class Autoscaler:
    """Collect → decide → journal → execute, on a timer or by hand.

    ``launch_worker(count, target_world)`` must start ``count`` new
    worker processes that call ``ElasticTrainer.run(..., join=True)``;
    ``release_worker(count, target_world)`` must signal ``count``
    members to leave (their exit drives the normal scale-down epoch).
    ``engines`` are :class:`~..serving.decode.DecodeEngine` instances
    whose KV-cache ``slots`` follow the same decisions via
    ``resize``.  Any actuator may be ``None``: the decision is still
    journaled, which is what the drills assert on.
    """

    def __init__(self, policy, telemetry_dir=None, hb_dir=None,
                 collect=None, world=None, launch_worker=None,
                 release_worker=None, engines=(), interval=10.0):
        self.policy = policy
        self.telemetry_dir = telemetry_dir
        self.hb_dir = hb_dir
        self._collect = collect
        self._world = world
        self.launch_worker = launch_worker
        self.release_worker = release_worker
        self.engines = list(engines)
        self.interval = float(interval)
        self.last_decision = None
        self._last_action_ts = None
        self._stop = threading.Event()
        self._thread = None

    def enabled(self):
        return self.policy is not None and autoscale_enabled()

    # -- observation ----------------------------------------------------

    def current_world(self):
        """World size from the newest membership record when a
        membership dir is wired, else the constructor's static value,
        else 1."""
        if self.hb_dir is not None:
            from . import elastic as _elastic

            _epoch, rec = _elastic.latest_epoch(self.hb_dir)
            if rec is not None and rec.get("members"):
                return len(rec["members"])
        return int(self._world) if self._world is not None else 1

    def _status(self):
        if self._collect is not None:
            return self._collect()
        if self.telemetry_dir is not None:
            from ..tools.monitor import collect_status

            return collect_status(self.telemetry_dir,
                                  hb_dir=self.hb_dir)
        return {}

    # -- the loop -------------------------------------------------------

    def poll_once(self, status=None, now=None):
        """One control-loop turn.  Returns the :class:`Decision`."""
        from ..observability import runtime as _obs

        now = time.time() if now is None else now
        world = self.current_world()
        slots = sum(e.slots for e in self.engines) \
            if self.engines else None
        if not self.enabled():
            decision = Decision(
                action=NOOP,
                reason="autoscaler disabled (PADDLE_TPU_AUTOSCALE=0 "
                       "or no SLOPolicy)",
                world=world, target_world=world, slots=slots,
                target_slots=slots, evidence={})
            self.last_decision = decision
            return decision
        if status is None:
            status = self._status()
        decision = self.policy.decide(
            status, world, now=now,
            last_action_ts=self._last_action_ts, slots=slots)
        _obs.record_autoscale_decision(
            decision.action, decision.reason, world=decision.world,
            target_world=decision.target_world,
            evidence=decision.evidence)
        self.last_decision = decision
        if self._execute(decision):
            self._last_action_ts = now
        return decision

    def _execute(self, decision):
        acted = False
        if decision.action == GROW:
            if self.launch_worker is not None \
                    and decision.target_world > decision.world:
                self.launch_worker(
                    decision.target_world - decision.world,
                    decision.target_world)
                acted = True
            acted = self._resize_engines(+1) or acted
        elif decision.action == SHRINK:
            if self.release_worker is not None \
                    and decision.target_world < decision.world:
                self.release_worker(
                    decision.world - decision.target_world,
                    decision.target_world)
                acted = True
            acted = self._resize_engines(-1) or acted
        return acted

    def _resize_engines(self, delta):
        acted = False
        for engine in self.engines:
            want = min(max(engine.slots + delta,
                           self.policy.min_slots),
                       self.policy.max_slots)
            if want != engine.slots:
                engine.resize(want)
                acted = True
        return acted

    # -- background operation -------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                pass           # a bad collect; next tick retries

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
