"""Deterministic, seeded fault injection for resilience testing.

The reference's fault story is exercised only by hand (kill a trainer,
corrupt a checkpoint, watch what happens); here faults are first-class and
reproducible: a spec string — env ``PADDLE_TPU_FAULT_SPEC`` or
:func:`set_fault_spec` — names *what* fails, *when*, and *how often*, and
every probabilistic decision draws from a seeded RNG so a chaos run can be
replayed bit-for-bit.

Spec grammar (``;``-separated faults, each ``kind@key=val,key=val``)::

    nan_grad@step=3                       # NaN into every @GRAD at step 3
    inf_grad@step=2,target=fc_0.w_0@GRAD  # +inf into one chosen gradient
    nan_loss@step=4                       # NaN into the loss value
    ckpt_write_fail@step=5,times=2        # transient IOError in ckpt save
    ckpt_read_fail@times=1                # transient IOError in ckpt load
    io_fail@target=write,p=0.5,seed=7     # probabilistic raw io.py faults
    compile_fail@times=1                  # simulated executor compile fail
    barrier_fail@times=1                  # transient fleet-bootstrap fail
    worker_kill@step=7,rank=1             # os._exit at step 7 on rank 1
    worker_hang@step=7,rank=0,secs=3600   # simulated hang (sleep)

Keys: ``step`` (training step to fire at; omitted = any step), ``rank``
(only this worker, default any; rank = ``PADDLE_TRAINER_ID``), ``times``
(max firings, default 1; ``times=0`` = unlimited), ``p`` (firing
probability per eligible occurrence, default 1.0), ``seed`` (RNG seed for
``p``), ``target`` (fnmatch pattern selecting gradient names / io
direction), ``value`` (``nan`` | ``inf`` | ``-inf`` | float, for value
faults), ``secs`` (hang duration).

Fault classes:

* **value faults** (``nan_grad``, ``inf_grad``, ``nan_loss``) corrupt
  values *inside* the jitted step via a fed per-fault gate vector, so the
  compiled function is reused across steps and the corruption is exactly
  as the guard would see a real one;
* **site faults** (``ckpt_write_fail``, ``ckpt_read_fail``, ``io_fail``,
  ``compile_fail``, ``barrier_fail``) raise :class:`TransientFault` at a
  named call site — the retry layer must absorb them;
* **process faults** (``worker_kill``, ``worker_hang``) terminate or
  stall the process at a training step — the watchdog layer must surface
  them as :class:`~paddle_tpu.resilience.watchdog.WorkerLostError`.

Step accounting: the Executor advances an internal run counter, but a
training loop should pin the authoritative step with :func:`set_step`
(the chaos CLI and tests do) so ``step=k`` means *its* step k regardless
of startup-program runs or resume offsets.
"""

import fnmatch
import os
import random
import time

__all__ = [
    "FaultInjected",
    "TransientFault",
    "Fault",
    "FaultInjector",
    "get_injector",
    "set_fault_spec",
    "reset_injector",
    "set_step",
    "GATE_FEED",
    "KILL_EXIT_CODE",
]

# feed name carrying the per-fault gate vector into the jitted step
GATE_FEED = "__fault_gate__"
# exit status of a worker_kill fault — distinguishable from real crashes
KILL_EXIT_CODE = 43

VALUE_KINDS = ("nan_grad", "inf_grad", "nan_loss")
SITE_KINDS = ("ckpt_write_fail", "ckpt_read_fail", "io_fail",
              "compile_fail", "barrier_fail")
PROCESS_KINDS = ("worker_kill", "worker_hang")

# site fault kind -> default call-site it fires at
_SITE_OF = {
    "ckpt_write_fail": "ckpt_write",
    "ckpt_read_fail": "ckpt_read",
    "compile_fail": "compile",
    "barrier_fail": "barrier",
    # io_fail: site io_<target>, target in {write, read} (default write)
}


class FaultInjected(RuntimeError):
    """Base class for every injected failure."""


class TransientFault(FaultInjected, OSError):
    """An injected *transient* failure (also an OSError so any generic
    io retry policy treats it as retryable)."""


def _record_fault(kind, step, site=None):
    """Count + journal an injected fault.  The journal treats
    ``fault-injected`` as urgent (synchronous flush) — worker_kill
    ``os._exit``\\ s immediately after, and the whole point is that the
    monitor can still see the fault."""
    try:
        from ..observability import runtime as _obs

        _obs.record_fault(kind, step=step, site=site)
    except Exception:  # noqa: BLE001 - telemetry never blocks a fault
        pass


def _parse_value(tok):
    t = tok.strip().lower()
    if t in ("nan",):
        return float("nan")
    if t in ("inf", "+inf"):
        return float("inf")
    if t == "-inf":
        return float("-inf")
    return float(tok)


class Fault:
    """One parsed spec entry; owns its firing budget and seeded RNG."""

    def __init__(self, kind, step=None, rank=None, times=None, p=1.0,
                 seed=0, target=None, value=None, secs=3600.0):
        if kind not in VALUE_KINDS + SITE_KINDS + PROCESS_KINDS:
            raise ValueError(
                "unknown fault kind %r (have %s)"
                % (kind, sorted(VALUE_KINDS + SITE_KINDS + PROCESS_KINDS)))
        self.kind = kind
        self.step = None if step is None else int(step)
        self.rank = None if rank is None else int(rank)
        # default: fire once (0 = unlimited)
        self.times = 1 if times is None else int(times)
        self.p = float(p)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.secs = float(secs)
        if target is None:
            if kind in ("nan_grad", "inf_grad"):
                target = "*@GRAD"
            elif kind == "io_fail":
                target = "write"
        self.target = target
        if value is None and kind in VALUE_KINDS:
            value = float("inf") if kind == "inf_grad" else float("nan")
        self.value = value
        self.fired = 0

    @classmethod
    def parse(cls, text):
        text = text.strip()
        if not text:
            raise ValueError("empty fault entry")
        kind, _, params = text.partition("@")
        kw = {}
        if params:
            for item in params.split(","):
                key, eq, val = item.partition("=")
                key = key.strip()
                if not eq or not key:
                    raise ValueError(
                        "malformed fault param %r in %r (want key=value)"
                        % (item, text))
                if key in ("step", "rank", "times", "seed"):
                    kw[key] = int(val)
                elif key in ("p", "secs"):
                    kw[key] = float(val)
                elif key == "value":
                    kw[key] = _parse_value(val)
                elif key == "target":
                    kw[key] = val.strip()
                else:
                    raise ValueError(
                        "unknown fault param %r in %r" % (key, text))
        return cls(kind.strip(), **kw)

    @property
    def site(self):
        if self.kind == "io_fail":
            return "io_" + (self.target or "write")
        return _SITE_OF.get(self.kind)

    def exhausted(self):
        return self.times > 0 and self.fired >= self.times

    def _eligible(self, step, rank):
        if self.exhausted():
            return False
        if self.step is not None and step is not None \
                and step != self.step:
            return False
        if self.rank is not None and rank is not None \
                and rank != self.rank:
            return False
        return True

    def should_fire(self, step=None, rank=None):
        """Decide (and consume budget on True)."""
        if not self._eligible(step, rank):
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        self.fired += 1
        return True

    def matches_name(self, name, loss_name=None):
        if self.kind == "nan_loss":
            pat = self.target or loss_name
            return pat is not None and fnmatch.fnmatchcase(name, pat)
        return self.target is not None \
            and fnmatch.fnmatchcase(name, self.target)

    def __repr__(self):
        parts = [self.kind]
        for k in ("step", "rank", "target"):
            v = getattr(self, k)
            if v is not None:
                parts.append("%s=%s" % (k, v))
        return "<Fault %s times=%d fired=%d>" % (
            " ".join(parts), self.times, self.fired)


def _default_rank():
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


class FaultInjector:
    """Parsed fault spec + firing state.  One per process (see
    :func:`get_injector`); a spec-less injector is inert and every hook
    is a cheap no-op."""

    def __init__(self, spec=None, rank=None, state_file=None):
        self.spec = spec or ""
        self.rank = _default_rank() if rank is None else int(rank)
        self.faults = []
        for entry in self.spec.split(";"):
            if entry.strip():
                self.faults.append(Fault.parse(entry))
        self._auto_step = 0
        self._pinned_step = None
        # firing budgets can span process restarts (a worker_kill is ONE
        # preemption, not one per incarnation): point
        # PADDLE_TPU_FAULT_STATE_FILE at a shared path and consumed
        # budgets persist across auto-resume restarts
        self.state_file = (state_file if state_file is not None
                           else os.environ.get(
                               "PADDLE_TPU_FAULT_STATE_FILE"))
        self._load_state()

    def _load_state(self):
        if not self.state_file or not os.path.exists(self.state_file):
            return
        import json

        try:
            with open(self.state_file) as f:
                state = json.load(f)
        except (ValueError, OSError):
            return
        if state.get("spec") != self.spec:
            # stale file from a run with a different spec (e.g. same
            # --ckpt-dir, new --spec): its positional counts are
            # meaningless here — start fresh rather than pre-exhaust
            return
        for f_obj, count in zip(self.faults, state.get("fired", [])):
            f_obj.fired = int(count)

    def _persist_state(self):
        if not self.state_file:
            return
        import json

        from .atomic import atomic_write

        try:
            atomic_write(
                self.state_file,
                lambda f: json.dump(
                    {"spec": self.spec,
                     "fired": [f_obj.fired for f_obj in self.faults]},
                    f),
                text=True)
        except OSError:
            pass  # fault accounting must never take the trainer down

    @property
    def active(self):
        return bool(self.faults)

    @property
    def trace_faults(self):
        return [f for f in self.faults if f.kind in VALUE_KINDS]

    # ---- step accounting ----
    def set_step(self, step):
        """Pin the authoritative training step (trainer loops should call
        this each iteration; unpinned, Executor.run calls auto-count)."""
        self._pinned_step = None if step is None else int(step)

    def current_step(self):
        return (self._pinned_step if self._pinned_step is not None
                else self._auto_step)

    # ---- hooks ----
    def on_step(self):
        """Called by the executor once per run dispatch: fires process
        faults (kill/hang) for the current step and returns it."""
        step = self.current_step()
        if self._pinned_step is None:
            self._auto_step += 1
        if not self.faults:
            return step
        for f in self.faults:
            if f.kind == "worker_kill" and f.should_fire(step, self.rank):
                import sys

                # persist BEFORE dying: the restarted incarnation must
                # see this preemption as already-spent
                self._persist_state()
                _record_fault("worker_kill", step)
                print("FAULT_INJECTED worker_kill step=%d rank=%d"
                      % (step, self.rank), file=sys.stderr, flush=True)
                os._exit(KILL_EXIT_CODE)
            elif f.kind == "worker_hang" \
                    and f.should_fire(step, self.rank):
                import sys

                self._persist_state()
                _record_fault("worker_hang", step)
                print("FAULT_INJECTED worker_hang step=%d rank=%d "
                      "secs=%s" % (step, self.rank, f.secs),
                      file=sys.stderr, flush=True)
                time.sleep(f.secs)
        return step

    def maybe_fire(self, site, step=None):
        """Raise :class:`TransientFault` if a site fault fires here."""
        if not self.faults:
            return
        if step is None:
            step = self.current_step()
        for f in self.faults:
            if f.site == site and f.should_fire(step, self.rank):
                self._persist_state()
                _record_fault(f.kind, step, site=site)
                raise TransientFault(
                    "injected %s at site %r (step %s, firing %d/%s)"
                    % (f.kind, site, step, f.fired,
                       f.times or "unlimited"))

    def gate_vector(self, step=None):
        """Per-trace-fault gate values (1.0 = corrupt this dispatch) as a
        host float32 array; consumes each firing fault's budget."""
        import numpy as np

        if step is None:
            step = self.current_step()
        gates = [1.0 if f.should_fire(step, self.rank) else 0.0
                 for f in self.trace_faults]
        if any(gates):
            self._persist_state()
            for f, g in zip(self.trace_faults, gates):
                if g:
                    _record_fault(f.kind, step)
        return np.asarray(gates, dtype=np.float32)

    def make_value_hook(self, gate, loss_name=None):
        """Trace-time hook ``(name, value) -> value`` corrupting values
        selected by the trace faults when their fed gate entry is hot.
        ``jnp.where`` (not ``gate * value``) so a cold gate is exactly
        identity — ``0 * nan`` would itself be nan."""
        import jax.numpy as jnp

        faults = self.trace_faults
        for f in faults:
            if f.kind == "nan_loss" and f.target is None \
                    and loss_name is None:
                import warnings

                warnings.warn(
                    "nan_loss fault has no target= and this program "
                    "records no loss var (built without "
                    "Optimizer.minimize?) — the fault will consume its "
                    "budget without corrupting anything",
                    RuntimeWarning, stacklevel=3)

        def hook(name, val):
            if not hasattr(val, "dtype") \
                    or not jnp.issubdtype(val.dtype, jnp.inexact):
                return val
            for i, f in enumerate(faults):
                if f.matches_name(name, loss_name=loss_name):
                    val = jnp.where(gate[i] > 0,
                                    jnp.asarray(f.value, val.dtype), val)
            return val

        return hook


_injector = None


def get_injector():
    """Process singleton, parsed from ``PADDLE_TPU_FAULT_SPEC`` on first
    use."""
    global _injector
    if _injector is None:
        _injector = FaultInjector(
            os.environ.get("PADDLE_TPU_FAULT_SPEC", ""))
    return _injector


def set_fault_spec(spec, rank=None):
    """Install a new spec (replacing the singleton); returns the new
    injector.  ``set_fault_spec(None)`` re-reads the env var lazily."""
    global _injector
    _injector = None if spec is None else FaultInjector(spec, rank=rank)
    return _injector


def reset_injector():
    """Drop all firing state and re-parse from the environment."""
    return set_fault_spec(None)


def set_step(step):
    """Pin the current training step on the process injector."""
    get_injector().set_step(step)
