"""Elastic training: re-plan, reshard, and resume on worker loss —
in-process, no restart, no lost hardware.

The recovery loop coordinates four existing layers when a peer dies or
wedges mid-run:

1. **Agree on the shrunk membership.**  Survivors converge on one
   epoch-numbered, write-once membership file in the shared heartbeat
   dir (:func:`agree_membership`).  ``os.link`` makes the write
   first-wins-atomic, so two workers can never adopt different worlds
   for the same epoch; a worker absent from the winning record evicts
   itself (:data:`ELASTIC_EVICTED_EXIT_CODE`).
2. **Re-plan and prove.**  ``parallel.auto_transpile`` re-prices the
   placement space for the shrunk :class:`~..parallel.ClusterSpec`;
   the winner carries the PR-3 deadlock proof (``deadlock == "ok"``)
   and the apply runs inside the PR-10 race bracket
   (``race_signatures`` / ``assert_no_new_races``) — both proved
   BEFORE any post-recovery step runs (:func:`plan_world`).
3. **Reshard the checkpoint.**  The new leader routes the latest
   manifest through :func:`~.reshard.reshard_checkpoint` when its
   recorded topology mismatches the new world; followers poll
   :func:`~.checkpoint.try_load_latest_checkpoint` (typed
   :class:`~.checkpoint.TopologyMismatchError` routing, never a silent
   skip) until the resharded version lands.
4. **Resume in-process**, journaling the incident chain
   ``worker-lost → replan → reshard → checkpoint-loaded → resume``
   that ``tools/monitor`` renders.

Why file-mediated gradient exchange?  The pinned jax/gloo runtime
cannot shrink a live distributed world in-process: the XLA coordination
service hard-terminates every survivor the moment
``jax.distributed.shutdown()`` runs with a dead peer (verified by
prototype) — "restart the job smaller" is exactly the failure mode this
module exists to remove.  So elastic workers never enter
``jax.distributed``: each runs single-process XLA, the transpiled
program is split at the optimizer boundary (the
``multi_batch_merge_pass`` partition the executor already uses for
gradient accumulation), and the ``c_allreduce_sum`` ops between head
and tail are realized as a deterministic file-rendezvous reduction
(:class:`GradExchange`, :func:`reduce_gradients`) in sorted-member
order.  The exchange wait doubles as the failure detector: a peer whose
heartbeat goes stale — or that stays silent past ``wedge_timeout``
while still beating — is a :class:`~.watchdog.WorkerLostError` verdict.

Caveats (documented contract): the split assumes forward/backward ops
do not mutate persistables (no sync-BN-style state in the head); plans
stamped ``zero1`` execute with unsharded optimizer state on
single-device elastic workers (execution-equivalent — the shard
remapping itself is exercised by the reshard round-trip tests on the
8-virtual-device harness).
"""

import collections
import json
import os
import time

import numpy as np

from . import checkpoint as _ckpt
from . import faults as _faults
from ..observability import tracing as _tr
from .watchdog import HeartbeatMonitor, HeartbeatWriter, WorkerLostError
from .watchdog import _record_lost

__all__ = [
    "ELASTIC_EVICTED_EXIT_CODE", "ElasticError", "ElasticEvictedError",
    "Membership", "agree_membership", "reduce_gradients",
    "SplitStep", "build_split", "plan_world", "GradExchange",
    "ElasticTrainer",
]

#: exit status of a worker excluded from the agreed shrunk membership
ELASTIC_EVICTED_EXIT_CODE = 45

_MEMBER_PREFIX = "member-"
_GRAD_PREFIX = "g-"


class ElasticError(RuntimeError):
    """Elastic recovery could not complete (membership timeout, plan
    proof failure, reshard wait exhausted)."""


class ElasticEvictedError(ElasticError):
    """This worker is not part of the agreed shrunk membership and must
    exit (:data:`ELASTIC_EVICTED_EXIT_CODE`)."""


# ---------------------------------------------------------------------------
# membership agreement
# ---------------------------------------------------------------------------

Membership = collections.namedtuple(
    "Membership", ["epoch", "members", "world", "lost", "writer",
                   "traceparent"])
# traceparent is optional so positional construction from before the
# tracing PR keeps working
Membership.__new__.__defaults__ = (None,)


def _member_path(dirname, epoch):
    return os.path.join(dirname, "%s%08d.json" % (_MEMBER_PREFIX,
                                                  int(epoch)))


def _write_once(path, record):
    """First-wins atomic publish: stage a private file, ``os.link`` it
    to the final name (fails EEXIST when a peer won the race), and
    return whatever record actually ended up at ``path``.  Two workers
    can therefore never read different membership for one epoch."""
    tmp = "%s.tmp-%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)
    except FileExistsError:
        pass
    finally:
        os.unlink(tmp)
    with open(path) as f:
        return json.load(f)


def agree_membership(dirname, rank, epoch, survivors, lost, reason="",
                     stale_timeout=5.0, timeout=60.0, poll=0.05):
    """Converge every survivor on one :class:`Membership` for ``epoch``.

    The lowest-ranked *alive* survivor writes the epoch's write-once
    record; everyone (writer included) returns what the file actually
    says.  Liveness of the would-be writer is judged by its heartbeat:
    if the presumptive leader dies while deciding, the next-lowest
    survivor takes over — the ladder ends with every waiter eligible,
    so a record always appears unless *all* lower ranks are dead AND we
    are dead, which is not a case this process observes.
    """
    os.makedirs(dirname, exist_ok=True)
    path = _member_path(dirname, epoch)
    survivors = sorted(int(r) for r in survivors)
    record = {
        "schema": 1, "epoch": int(epoch), "members": survivors,
        "world": len(survivors), "lost": sorted(int(r) for r in lost),
        "reason": str(reason)[:500], "writer": int(rank),
        "ts": time.time(),
        # the writer's trace rides in the record so every survivor can
        # join ONE recovery trace even if the drill env was not set
        "traceparent": _tr.current_traceparent(),
    }
    monitor = HeartbeatMonitor(
        dirname, [r for r in survivors if r != rank],
        timeout=stale_timeout, boot_grace=stale_timeout)
    deadline = time.time() + timeout
    while True:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    got = json.load(f)
                break
            except ValueError:
                # racing the winner's link: visible-but-unreadable
                # cannot happen (link publishes a complete file), so a
                # parse error is a torn leftover — retry briefly
                time.sleep(poll)
        stale = set(monitor.stale_ranks())
        alive = [r for r in survivors if r == rank or r not in stale]
        if alive and alive[0] == rank:
            got = _write_once(path, record)
            break
        if time.time() > deadline:
            raise ElasticError(
                "membership for epoch %d did not appear within %.1fs "
                "(waiting on writer among %s)" % (epoch, timeout, alive))
        time.sleep(poll)
    return Membership(epoch=int(got["epoch"]),
                      members=[int(r) for r in got["members"]],
                      world=int(got["world"]),
                      lost=[int(r) for r in got.get("lost", [])],
                      writer=int(got.get("writer", -1)),
                      traceparent=got.get("traceparent"))


# ---------------------------------------------------------------------------
# program split at the optimizer boundary
# ---------------------------------------------------------------------------

SplitStep = collections.namedtuple(
    "SplitStep", ["head", "tail", "grad_names", "pre_scale",
                  "passthrough"])


def build_split(program):
    """Split a GradAllReduce-transpiled ``program`` into a *head* clone
    (forward + backward, collectives removed) and a *tail* clone
    (optimizer ops, reduced gradients fed by name).

    Follows the executor's ``_accum_partition`` contract: the cut is the
    first ``op_role == "optimize"`` op; non-persistable head outputs the
    tail reads (``passthrough``) ride the fetch/feed path, persistable
    ones flow through the scope.  ``grad_names`` are the (in-place)
    outputs of the removed ``c_allreduce_sum`` ops — exactly the
    gradients the optimizer consumes — and ``pre_scale`` is their
    recorded averaging factor.  Returns ``None`` when the program has no
    collectives (world 1 / "single" plan): run it whole.
    """
    block = program.global_block()
    ops = block.ops
    ar_ops = [op for op in ops if op.type == "c_allreduce_sum"]
    if not ar_ops:
        return None
    grad_names = []
    for op in ar_ops:
        for n in op.output_arg_names:
            if n not in grad_names:
                grad_names.append(n)
    pre_scale = float(ar_ops[0].attrs.get("pre_scale", 1.0))
    split = next((i for i, op in enumerate(ops)
                  if op.attrs.get("op_role") == "optimize"), len(ops))

    head_prog = program.clone()
    hb = head_prog.global_block()
    hb.ops = [op for op in hb.ops[:split]
              if op.type != "c_allreduce_sum"]
    head_prog._bump_version()

    tail_prog = program.clone()
    tb = tail_prog.global_block()
    tb.ops = list(tb.ops[split:])
    tail_prog._bump_version()

    head_written = set()
    for op in hb.ops:
        head_written.update(op.output_arg_names)
    passthrough = []
    for op in tb.ops:
        for n in op.input_arg_names:
            if not n or n in grad_names or n not in head_written \
                    or n in passthrough:
                continue
            v = block._find_var_recursive(n)
            if v is not None and v.persistable:
                continue  # scope carries it between the two runs
            passthrough.append(n)
    return SplitStep(head=head_prog, tail=tail_prog,
                     grad_names=grad_names, pre_scale=pre_scale,
                     passthrough=passthrough)


def reduce_gradients(per_member, scale):
    """Deterministic mirror of the on-disk exchange: float32 sum of each
    gradient over ``per_member`` (dicts in sorted-member order), scaled
    by ``scale``, cast back to the local dtype.  The in-process oracle
    and the distributed workers share this one reduction, so their
    trajectories can be compared within fp tolerance, not luck."""
    if not per_member:
        return {}
    out = {}
    for name, local in per_member[0].items():
        local = np.asarray(local)
        acc = np.zeros(local.shape, dtype=np.float32)
        for contrib in per_member:
            acc = acc + np.asarray(contrib[name], dtype=np.float32)
        out[name] = (acc * float(scale)).astype(local.dtype, copy=False)
    return out


def plan_world(program, startup_program, world, rank_index=0,
               batch_size=None):
    """Clone + re-plan ``program`` for ``world`` chips and prove the
    result safe: ``auto_transpile`` must return a deadlock-proved winner
    and the apply must introduce no new race signatures.  The elastic
    loop additionally pins the data-parallel family — whatever plan the
    planner prefers on paper, a shrunk *live* world must exchange
    gradients, so a "single" standin at world > 1 gets the
    GradAllReduce transpile at the full membership degree.

    Returns ``(train_prog, startup_prog, split, result, applied)``;
    ``split`` is None for world 1."""
    from ..parallel.planner import (apply_plan, auto_transpile,
                                    resolve_cluster_spec)
    from ..static_analysis.concurrency import (assert_no_new_races,
                                               race_signatures)
    from ..transpiler.collective import GradAllReduce

    world = int(world)
    prog = program.clone()
    startup = startup_program.clone() if startup_program is not None \
        else None
    spec = resolve_cluster_spec(chips=world)
    result = auto_transpile(prog, spec, startup_program=startup,
                            batch_size=batch_size)
    if not result.deadlock_free:
        raise ElasticError(
            "re-planned schedule for world=%d failed the deadlock "
            "proof: %s" % (world, result.plan.status))
    baseline = race_signatures(prog)
    applied = apply_plan(prog, result, startup_program=startup,
                         rank=rank_index)
    if world > 1 and not any(op.type == "c_allreduce_sum"
                             for op in prog.global_block().ops):
        GradAllReduce().transpile(program=prog, startup_program=startup,
                                  rank=rank_index, nranks=world)
    assert_no_new_races(prog, baseline,
                        "elastic re-plan (world=%d)" % world)
    return prog, startup, build_split(prog), result, applied


# ---------------------------------------------------------------------------
# file-rendezvous gradient exchange
# ---------------------------------------------------------------------------

def _grad_fname(epoch, step, rank):
    return "%se%04d-s%08d-r%d.npz" % (_GRAD_PREFIX, int(epoch),
                                      int(step), int(rank))


class GradExchange:
    """Deterministic all-reduce through a shared directory.

    Each member atomically publishes its local gradients for
    ``(epoch, step)`` and assembles the reduction from every member's
    file in sorted-member order (:func:`reduce_gradients`).  The wait
    for a peer's file IS the rendezvous barrier and the failure
    detector: a peer whose heartbeat goes stale, or that stays silent
    past ``wedge_timeout`` while still beating (alive but stuck), is
    reported as :class:`WorkerLostError` — the verdict the elastic loop
    recovers from.  Files from ``step - 2`` are reclaimed on each
    publish (every member passing the ``step - 1`` rendezvous proves
    they were consumed)."""

    def __init__(self, dirname, rank, members, monitor,
                 wedge_timeout=60.0, poll=0.02):
        self.dirname = dirname
        self.rank = int(rank)
        self.members = sorted(int(m) for m in members)
        self.monitor = monitor
        self.wedge_timeout = float(wedge_timeout)
        self.poll = float(poll)
        os.makedirs(dirname, exist_ok=True)

    def _publish(self, epoch, step, arrays):
        final = os.path.join(self.dirname,
                             _grad_fname(epoch, step, self.rank))
        tmp = "%s.tmp-%d" % (final, os.getpid())
        payload = {n: np.asarray(v) for n, v in arrays.items()}
        # traceparent rides in-band so a peer can link its exchange
        # span to ours; stripped before reduction (reduce_gradients
        # never sees it)
        tp = _tr.current_traceparent()
        if tp:
            payload["__traceparent__"] = np.asarray(tp)
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, final)
        old = os.path.join(self.dirname,
                           _grad_fname(epoch, step - 2, self.rank))
        if step >= 2 and os.path.exists(old):
            try:
                os.unlink(old)
            except OSError:
                pass

    def _wait_peer(self, epoch, step, peer, deadline):
        path = os.path.join(self.dirname, _grad_fname(epoch, step, peer))
        while not os.path.exists(path):
            stale = set(self.monitor.stale_ranks()) \
                & set(self.members)
            if stale:
                _record_lost(sorted(stale),
                             "heartbeat stale during gradient exchange "
                             "(epoch %d step %d)" % (epoch, step))
                raise WorkerLostError(
                    "worker rank(s) %s lost during gradient exchange at "
                    "step %d" % (sorted(stale), step),
                    ranks=sorted(stale))
            if time.time() > deadline:
                _record_lost([peer],
                             "wedged: heartbeat fresh but no gradients "
                             "for %.1fs (epoch %d step %d)"
                             % (self.wedge_timeout, epoch, step))
                raise WorkerLostError(
                    "worker rank %d wedged: alive but produced no "
                    "gradients for step %d within %.1fs"
                    % (peer, step, self.wedge_timeout), ranks=[peer])
            time.sleep(self.poll)
        return path

    def allreduce(self, epoch, step, grads, scale):
        """Publish ``grads`` and return the scaled sorted-member-order
        reduction over all members' contributions."""
        xspan = _tr.span("elastic.exchange", epoch=int(epoch),
                         step=int(step), members=len(self.members))
        with xspan:
            self._publish(epoch, step, grads)
            per_member = []
            peer_traces = {}
            deadline = time.time() + self.wedge_timeout
            for member in self.members:
                if member == self.rank:
                    per_member.append(grads)
                    continue
                path = self._wait_peer(epoch, step, member, deadline)
                with np.load(path) as z:
                    contrib = {n: z[n] for n in z.files
                               if n != "__traceparent__"}
                    if "__traceparent__" in z.files:
                        peer_traces[member] = str(z["__traceparent__"])
                per_member.append(contrib)
            if peer_traces and xspan.recording:
                xspan.set_attr("peer_traceparents", peer_traces)
            return reduce_gradients(per_member, scale)

    def sweep(self, keep_epoch):
        """Drop this rank's files from epochs before ``keep_epoch``
        (adopting a new membership obsoletes every older rendezvous)."""
        prefix = "%se" % _GRAD_PREFIX
        suffix = "-r%d.npz" % self.rank
        try:
            names = os.listdir(self.dirname)
        except OSError:
            return
        for name in names:
            if not (name.startswith(prefix) and name.endswith(suffix)):
                continue
            try:
                epoch = int(name[len(prefix):].split("-", 1)[0])
            except ValueError:
                continue
            if epoch < keep_epoch:
                try:
                    os.unlink(os.path.join(self.dirname, name))
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# the elastic trainer
# ---------------------------------------------------------------------------

class ElasticTrainer:
    """Own the train loop so recovery can rewind it.

    ``run(total_steps, make_feed)`` executes the split step —
    head (forward+backward) → file all-reduce → tail (optimizer) —
    checkpointing from the leader with the membership topology stamped
    into the manifest.  A :class:`WorkerLostError` anywhere in the step
    triggers the four-layer recovery *in this process*; the step that
    was interrupted re-runs under the new world.

    ``make_feed(step, index, world)`` receives the member's POSITION in
    the sorted membership, not its original rank: a constant global
    batch sliced by index keeps the global gradient identical across
    world sizes (equal slices assumed), which is what makes the
    shrunk-world oracle comparison in ``tools/chaos --elastic`` exact
    up to fp reassociation.
    """

    def __init__(self, program, startup_program, executor, rank, world,
                 workdir, fetch_list=(), batch_size=None, ckpt_every=1,
                 retain=None, hb_interval=0.25, stale_timeout=3.0,
                 wedge_timeout=60.0, state=None):
        self.base_program = program
        self.base_startup = startup_program
        self.exe = executor
        self.rank = int(rank)
        self.workdir = workdir
        self.hb_dir = os.path.join(workdir, "hb")
        self.exchange_dir = os.path.join(workdir, "exchange")
        self.ckpt_dir = os.path.join(workdir, "ckpt")
        self.fetch_list = [getattr(v, "name", v) for v in fetch_list]
        self.batch_size = batch_size
        self.ckpt_every = max(int(ckpt_every), 1)
        self.retain = retain
        self.hb_interval = float(hb_interval)
        self.stale_timeout = float(stale_timeout)
        self.wedge_timeout = float(wedge_timeout)
        self.state = dict(state or {})

        self.epoch = 0
        self.members = list(range(int(world)))
        self.step = 0
        self.train_prog = None
        self.split = None
        self.zero1 = False
        self._hb = None
        self._monitor = None
        self._exchange = None
        self._recovering_since = None
        for d in (self.hb_dir, self.exchange_dir, self.ckpt_dir):
            os.makedirs(d, exist_ok=True)

    # -- membership-derived views -------------------------------------

    @property
    def world(self):
        return len(self.members)

    @property
    def index(self):
        return self.members.index(self.rank)

    def _is_leader(self):
        return self.rank == min(self.members)

    def _topology(self):
        return {"world": self.world, "zero1": bool(self.zero1)}

    def _adopt_membership(self, membership):
        """Install an agreed membership: peers list, watchdog, exchange,
        and the fleet env contract (``PADDLE_TRAINER_ID`` /
        ``PADDLE_TRAINERS_NUM``) that role makers and ``_is_primary``
        read — after a leader loss the new leader must also *look*
        primary to every downstream layer."""
        self.epoch = membership.epoch
        self.members = list(membership.members)
        if self.rank not in self.members:
            raise ElasticEvictedError(
                "rank %d is not part of membership epoch %d %s — "
                "exiting with ELASTIC_EVICTED_EXIT_CODE"
                % (self.rank, self.epoch, self.members))
        os.environ["PADDLE_TRAINER_ID"] = str(self.index)
        os.environ["PADDLE_TRAINERS_NUM"] = str(self.world)
        peers = [m for m in self.members if m != self.rank]
        self._monitor = HeartbeatMonitor(
            self.hb_dir, peers, timeout=self.stale_timeout,
            interval=self.hb_interval, boot_grace=self.wedge_timeout)
        self._exchange = GradExchange(
            self.exchange_dir, self.rank, self.members, self._monitor,
            wedge_timeout=self.wedge_timeout)
        self._exchange.sweep(self.epoch)

    # -- planning / restore --------------------------------------------

    def _plan(self):
        t0 = time.perf_counter()
        old_world = self.world if self.train_prog is not None else None
        with _tr.span("elastic.replan", epoch=self.epoch,
                      world=self.world):
            (self.train_prog, startup, self.split, result,
             applied) = plan_world(self.base_program, self.base_startup,
                                   self.world, rank_index=self.index,
                                   batch_size=self.batch_size)
        self.zero1 = bool(getattr(self.train_prog,
                                  "_shard_optimizer_state", False))
        if old_world is not None:
            from ..observability import runtime as _obs

            _obs.record_replan(
                self.epoch, old_world, self.world, applied.describe(),
                (time.perf_counter() - t0) * 1000.0)
        return startup

    def _topology_compatible(self, recorded):
        expected = self._topology()
        return not any(k in recorded and recorded[k] != expected[k]
                       for k in expected)

    def _restore(self, recovery):
        """Load the newest checkpoint at the CURRENT topology.  The
        leader reshards a mismatched latest version first; followers
        wait for the resharded manifest to land rather than loading a
        stale layout or silently falling back to an older version."""
        topo = self._topology()
        if self._is_leader():
            versions = _ckpt.list_checkpoints(self.ckpt_dir)
            if versions:
                _step, path = versions[0]
                recorded = _ckpt.read_topology(path)
                if recorded is not None \
                        and not self._topology_compatible(recorded):
                    from .reshard import reshard_checkpoint

                    reshard_checkpoint(path, topo)
        else:
            self._await_resharded(recovery)
        info = _ckpt.try_load_latest_checkpoint(
            self.exe, self.ckpt_dir, main_program=self.train_prog,
            expected_topology=topo)
        if info is not None:
            self.step = int(info.state.get("step", info.step)) + 1
            self.state.update(info.state.get("extra", {}))
        elif not recovery:
            self.step = 0
        # on recovery with no checkpoint at all, every survivor keeps
        # its in-memory state: the tail applied identical reduced
        # gradients everywhere, so replicated state is still consistent
        # and self.step already points at the interrupted step
        return info

    def _await_resharded(self, recovery, none_grace=2.0):
        """Follower side of the reshard rendezvous: poll until the
        newest version's recorded topology fits this world.  A brief
        empty-listing window is tolerated (the leader's save-aside
        replacement renames the dir out and back); a persistent empty
        root means there is nothing to restore."""
        deadline = time.time() + self.wedge_timeout
        none_since = None
        while True:
            versions = _ckpt.list_checkpoints(self.ckpt_dir)
            if versions:
                none_since = None
                try:
                    recorded = _ckpt.read_topology(versions[0][1])
                except _ckpt.CorruptCheckpointError:
                    recorded = None  # racing the replacement rename
                if recorded is None \
                        or self._topology_compatible(recorded):
                    return
            else:
                if not recovery:
                    return  # fresh start: nothing will appear
                if none_since is None:
                    none_since = time.time()
                elif time.time() - none_since > none_grace:
                    return
            if time.time() > deadline:
                raise ElasticError(
                    "timed out after %.1fs waiting for the leader to "
                    "reshard the checkpoint for %s"
                    % (self.wedge_timeout, self._topology()))
            time.sleep(0.05)

    # -- the step -------------------------------------------------------

    def _run_step(self, make_feed):
        step = self.step
        _faults.set_step(step)
        self._hb.beat()
        feed = make_feed(step, self.index, self.world)
        if self.split is None:
            return self.exe.run(program=self.train_prog, feed=feed,
                                fetch_list=list(self.fetch_list))
        sp = self.split
        head_fetch = (list(self.fetch_list) + sp.grad_names
                      + sp.passthrough)
        out = self.exe.run(program=sp.head, feed=feed,
                           fetch_list=head_fetch)
        nf = len(self.fetch_list)
        ng = len(sp.grad_names)
        fetches = out[:nf]
        grads = dict(zip(sp.grad_names, out[nf:nf + ng]))
        passthrough = dict(zip(sp.passthrough, out[nf + ng:]))
        reduced = self._exchange.allreduce(self.epoch, step, grads,
                                           sp.pre_scale)
        tail_feed = dict(passthrough)
        tail_feed.update(reduced)
        self.exe.run(program=sp.tail, feed=tail_feed, fetch_list=[])
        return fetches

    def _maybe_checkpoint(self):
        if not self._is_leader() \
                or (self.step + 1) % self.ckpt_every != 0:
            return
        _ckpt.save_checkpoint(
            self.exe, self.ckpt_dir, main_program=self.train_prog,
            step=self.step,
            state={"step": self.step, "extra": self.state},
            retain=self.retain, all_ranks=True,
            topology=self._topology())

    # -- recovery -------------------------------------------------------

    def _recover(self, err):
        t0 = time.perf_counter()
        lost = sorted(set(int(r) for r in err.ranks)
                      & set(self.members))
        if not lost:
            raise err  # a loss verdict naming no current member
        survivors = [m for m in self.members if m not in lost]
        if not survivors or self.rank not in survivors:
            raise ElasticEvictedError(
                "rank %d was declared lost (%s) — exiting"
                % (self.rank, err))
        with _tr.span("elastic.recover", epoch=self.epoch + 1,
                      lost=lost, survivors=len(survivors)):
            with _tr.span("elastic.agree"):
                membership = agree_membership(
                    self.hb_dir, self.rank, self.epoch + 1, survivors,
                    lost, reason=str(err),
                    stale_timeout=self.stale_timeout,
                    timeout=self.wedge_timeout)
            self._adopt_membership(membership)
            self._plan()
            with _tr.span("elastic.restore"):
                self._restore(recovery=True)
        self._recovering_since = t0
        _faults.set_step(self.step)

    def _after_step(self):
        if self._recovering_since is not None:
            from ..observability import runtime as _obs

            _obs.record_elastic_recovery(
                self.epoch, self.step, self.world,
                (time.perf_counter() - self._recovering_since)
                * 1000.0)
            self._recovering_since = None

    # -- entry point ----------------------------------------------------

    def run(self, total_steps, make_feed, on_step=None):
        """Train ``total_steps`` steps, recovering from worker loss
        in-process.  ``on_step(step, fetches, trainer)`` observes each
        completed step.  Returns the final step count."""
        membership = Membership(
            epoch=self.epoch, members=list(self.members),
            world=len(self.members), lost=[], writer=self.rank)
        self._hb = HeartbeatWriter(self.hb_dir, self.rank,
                                   interval=self.hb_interval).start()
        # the worker's root span: joins the drill/driver trace when
        # PADDLE_TPU_TRACEPARENT is in the env (the remote-parent
        # fallback), so one trace covers every rank through recovery.
        # Rank reaches this process as an argument, not env, and the
        # fleet env contract is only written at membership adoption —
        # stamp spans with the stable elastic rank explicitly (the
        # post-recovery index would mislabel survivors of a leader
        # loss).
        if _tr.tracing_enabled():
            _tr.set_rank(self.rank)
        with _tr.span("elastic.worker", rank=self.rank,
                      world=len(self.members)):
            try:
                self._adopt_membership(membership)
                startup = self._plan()
                if startup is not None:
                    self.exe.run(program=startup)
                self._restore(recovery=False)
                while self.step < int(total_steps):
                    try:
                        with _tr.span("elastic.step", step=self.step,
                                      epoch=self.epoch):
                            fetches = self._run_step(make_feed)
                    except WorkerLostError as e:
                        self._recover(e)
                        continue
                    self._after_step()
                    self._maybe_checkpoint()
                    if on_step is not None:
                        on_step(self.step, fetches, self)
                    self.step += 1
                return self.step
            finally:
                self._hb.stop()
