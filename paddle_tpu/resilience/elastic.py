"""Elastic training: re-plan, reshard, and resume on worker loss —
in-process, no restart, no lost hardware.

The recovery loop coordinates four existing layers when a peer dies or
wedges mid-run:

1. **Agree on the shrunk membership.**  Survivors converge on one
   epoch-numbered, write-once membership file in the shared heartbeat
   dir (:func:`agree_membership`).  ``os.link`` makes the write
   first-wins-atomic, so two workers can never adopt different worlds
   for the same epoch; a worker absent from the winning record evicts
   itself (:data:`ELASTIC_EVICTED_EXIT_CODE`).
2. **Re-plan and prove.**  ``parallel.auto_transpile`` re-prices the
   placement space for the shrunk :class:`~..parallel.ClusterSpec`;
   the winner carries the PR-3 deadlock proof (``deadlock == "ok"``)
   and the apply runs inside the PR-10 race bracket
   (``race_signatures`` / ``assert_no_new_races``) — both proved
   BEFORE any post-recovery step runs (:func:`plan_world`).
3. **Reshard the checkpoint.**  The new leader routes the latest
   manifest through :func:`~.reshard.reshard_checkpoint` when its
   recorded topology mismatches the new world; followers poll
   :func:`~.checkpoint.try_load_latest_checkpoint` (typed
   :class:`~.checkpoint.TopologyMismatchError` routing, never a silent
   skip) until the resharded version lands.
4. **Resume in-process**, journaling the incident chain
   ``worker-lost → replan → reshard → checkpoint-loaded → resume``
   that ``tools/monitor`` renders.

Why file-mediated gradient exchange?  The pinned jax/gloo runtime
cannot shrink a live distributed world in-process: the XLA coordination
service hard-terminates every survivor the moment
``jax.distributed.shutdown()`` runs with a dead peer (verified by
prototype) — "restart the job smaller" is exactly the failure mode this
module exists to remove.  So elastic workers never enter
``jax.distributed``: each runs single-process XLA, the transpiled
program is split at the optimizer boundary (the
``multi_batch_merge_pass`` partition the executor already uses for
gradient accumulation), and the ``c_allreduce_sum`` ops between head
and tail are realized as a deterministic file-rendezvous reduction
(:class:`GradExchange`, :func:`reduce_gradients`) in sorted-member
order.  The exchange wait doubles as the failure detector: a peer whose
heartbeat goes stale — or that stays silent past ``wedge_timeout``
while still beating — is a :class:`~.watchdog.WorkerLostError` verdict.

Caveats (documented contract): the split assumes forward/backward ops
do not mutate persistables (no sync-BN-style state in the head); plans
stamped ``zero1`` execute with unsharded optimizer state on
single-device elastic workers (execution-equivalent — the shard
remapping itself is exercised by the reshard round-trip tests on the
8-virtual-device harness).

Scale-UP mirrors the shrink machinery with a two-phase admission:

1. **Join request.**  A joiner posts a write-once
   ``join-<epoch>-r<rank>.json`` at the membership dir
   (:func:`request_join`) and heartbeats while it waits — staleness
   evicts it from admission exactly like it evicts a member from the
   fleet.
2. **Admit + warm up.**  The epoch writer (lowest-ranked alive member,
   same takeover ladder) publishes ``admit-<epoch+1>.json`` naming the
   joiners.  Each joiner then compiles and dry-runs its re-planned
   worker program BEFORE acknowledging with a ``ready`` marker; the
   fleet keeps stepping at the old epoch the whole time, and a joiner
   that dies or wedges mid-warm-up is dropped by heartbeat staleness —
   the admission rolls forward without it.
3. **Transition.**  Once every surviving joiner is ready the leader
   writes ``member-<epoch+1>`` carrying ``start_step = leader.step +
   2``.  The exchange is lockstep (no member begins step S+1 before
   every member finished the step-S rendezvous), so a record written
   at the leader's boundary S is visible to all members by their
   boundary S+1 < start_step — everyone re-plans up, the leader
   reshards the freshest checkpoint N→N+1 through the
   direction-agnostic reshard, and the grown world resumes at
   ``start_step`` together.

:mod:`.autoscale` drives this loop (and ``DecodeEngine`` slot counts)
from monitor-collected SLO signals.
"""

import collections
import json
import os
import time

import numpy as np

from . import checkpoint as _ckpt
from . import faults as _faults
from ..observability import tracing as _tr
from .watchdog import (HeartbeatMonitor, HeartbeatWriter,
                       WorkerLostError, read_heartbeat)
from .watchdog import _record_lost

__all__ = [
    "ELASTIC_EVICTED_EXIT_CODE", "ElasticError", "ElasticEvictedError",
    "Membership", "agree_membership", "reduce_gradients",
    "SplitStep", "build_split", "plan_world", "GradExchange",
    "ElasticTrainer", "latest_epoch", "request_join", "pending_joins",
    "gc_epoch_files", "join_enabled",
]

#: exit status of a worker excluded from the agreed shrunk membership
ELASTIC_EVICTED_EXIT_CODE = 45

_MEMBER_PREFIX = "member-"
_GRAD_PREFIX = "g-"
_JOIN_PREFIX = "join-"
_ADMIT_PREFIX = "admit-"
_READY_PREFIX = "ready-"


class ElasticError(RuntimeError):
    """Elastic recovery could not complete (membership timeout, plan
    proof failure, reshard wait exhausted)."""


class ElasticEvictedError(ElasticError):
    """This worker is not part of the agreed shrunk membership and must
    exit (:data:`ELASTIC_EVICTED_EXIT_CODE`)."""


# ---------------------------------------------------------------------------
# membership agreement
# ---------------------------------------------------------------------------

Membership = collections.namedtuple(
    "Membership", ["epoch", "members", "world", "lost", "writer",
                   "traceparent"])
# traceparent is optional so positional construction from before the
# tracing PR keeps working
Membership.__new__.__defaults__ = (None,)


def _member_path(dirname, epoch):
    return os.path.join(dirname, "%s%08d.json" % (_MEMBER_PREFIX,
                                                  int(epoch)))


def _write_once(path, record):
    """First-wins atomic publish: stage a private file, ``os.link`` it
    to the final name (fails EEXIST when a peer won the race), and
    return whatever record actually ended up at ``path``.  Two workers
    can therefore never read different membership for one epoch."""
    tmp = "%s.tmp-%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)
    except FileExistsError:
        pass
    finally:
        os.unlink(tmp)
    with open(path) as f:
        return json.load(f)


def agree_membership(dirname, rank, epoch, survivors, lost, reason="",
                     stale_timeout=5.0, timeout=60.0, poll=0.05):
    """Converge every survivor on one :class:`Membership` for ``epoch``.

    The lowest-ranked *alive* survivor writes the epoch's write-once
    record; everyone (writer included) returns what the file actually
    says.  Liveness of the would-be writer is judged by its heartbeat:
    if the presumptive leader dies while deciding, the next-lowest
    survivor takes over — the ladder ends with every waiter eligible,
    so a record always appears unless *all* lower ranks are dead AND we
    are dead, which is not a case this process observes.
    """
    os.makedirs(dirname, exist_ok=True)
    path = _member_path(dirname, epoch)
    survivors = sorted(int(r) for r in survivors)
    record = {
        "schema": 1, "epoch": int(epoch), "members": survivors,
        "world": len(survivors), "lost": sorted(int(r) for r in lost),
        "reason": str(reason)[:500], "writer": int(rank),
        "ts": time.time(),
        # the writer's trace rides in the record so every survivor can
        # join ONE recovery trace even if the drill env was not set
        "traceparent": _tr.current_traceparent(),
    }
    monitor = HeartbeatMonitor(
        dirname, [r for r in survivors if r != rank],
        timeout=stale_timeout, boot_grace=stale_timeout)
    deadline = time.time() + timeout
    while True:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    got = json.load(f)
                break
            except ValueError:
                # racing the winner's link: visible-but-unreadable
                # cannot happen (link publishes a complete file), so a
                # parse error is a torn leftover — retry briefly
                time.sleep(poll)
        stale = set(monitor.stale_ranks())
        alive = [r for r in survivors if r == rank or r not in stale]
        if alive and alive[0] == rank:
            got = _write_once(path, record)
            break
        if time.time() > deadline:
            raise ElasticError(
                "membership for epoch %d did not appear within %.1fs "
                "(waiting on writer among %s)" % (epoch, timeout, alive))
        time.sleep(poll)
    return Membership(epoch=int(got["epoch"]),
                      members=[int(r) for r in got["members"]],
                      world=int(got["world"]),
                      lost=[int(r) for r in got.get("lost", [])],
                      writer=int(got.get("writer", -1)),
                      traceparent=got.get("traceparent"))


def _membership_from_record(rec):
    return Membership(epoch=int(rec["epoch"]),
                      members=[int(r) for r in rec["members"]],
                      world=int(rec["world"]),
                      lost=[int(r) for r in rec.get("lost", [])],
                      writer=int(rec.get("writer", -1)),
                      traceparent=rec.get("traceparent"))


# ---------------------------------------------------------------------------
# join protocol: request / admit / ready files + epoch-scoped GC
# ---------------------------------------------------------------------------

def _join_path(dirname, epoch, rank):
    return os.path.join(dirname, "%s%08d-r%d.json"
                        % (_JOIN_PREFIX, int(epoch), int(rank)))


def _admit_path(dirname, epoch):
    return os.path.join(dirname, "%s%08d.json" % (_ADMIT_PREFIX,
                                                  int(epoch)))


def _ready_path(dirname, epoch, rank):
    return os.path.join(dirname, "%s%08d-r%d.json"
                        % (_READY_PREFIX, int(epoch), int(rank)))


def join_enabled():
    """Scale-up admission master switch (``PADDLE_TPU_ELASTIC_JOIN``,
    default on).  With it off — or simply with no join files on disk —
    the scale-down path is untouched."""
    return os.environ.get("PADDLE_TPU_ELASTIC_JOIN", "1") \
        .strip().lower() not in ("0", "false", "off")


def latest_epoch(dirname):
    """Newest ``member-<epoch>`` record in ``dirname`` as
    ``(epoch, record_dict)``.  ``(None, None)`` when no record exists;
    a present-but-unreadable record returns ``(epoch, None)`` (caller
    retries — it is mid-publish)."""
    best = None
    try:
        names = os.listdir(dirname)
    except OSError:
        return None, None
    for name in names:
        if not (name.startswith(_MEMBER_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            epoch = int(name[len(_MEMBER_PREFIX):-len(".json")])
        except ValueError:
            continue
        best = epoch if best is None else max(best, epoch)
    if best is None:
        return None, None
    try:
        with open(_member_path(dirname, best)) as f:
            return best, json.load(f)
    except (OSError, ValueError):
        return best, None


def request_join(dirname, rank, epoch, traceparent=None):
    """Post the write-once join request asking admission into the epoch
    AFTER ``epoch`` (the newest membership the joiner observed).
    Returns whatever record won the slot."""
    os.makedirs(dirname, exist_ok=True)
    record = {
        "schema": 1, "rank": int(rank), "epoch": int(epoch),
        "ts": time.time(),
        "traceparent": traceparent or _tr.current_traceparent(),
    }
    return _write_once(_join_path(dirname, epoch, rank), record)


def pending_joins(dirname, epoch, stale_timeout=5.0, now=None):
    """Ranks with a join request posted against ``epoch`` whose
    heartbeat is fresh — a joiner that died after posting never makes
    it into an admission round."""
    now = time.time() if now is None else now
    prefix = "%s%08d-r" % (_JOIN_PREFIX, int(epoch))
    out = []
    try:
        names = os.listdir(dirname)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        try:
            rank = int(name[len(prefix):-len(".json")])
        except ValueError:
            continue
        hb = read_heartbeat(dirname, rank)
        if hb is not None and now - hb["mtime"] <= stale_timeout:
            out.append(rank)
    return sorted(out)


def _protocol_epoch(name):
    """Epoch encoded in a membership-protocol or grad-exchange file
    name, or None for files outside the epoch-scoped families."""
    for prefix in (_MEMBER_PREFIX, _JOIN_PREFIX, _ADMIT_PREFIX,
                   _READY_PREFIX):
        if name.startswith(prefix):
            digits = name[len(prefix):].split("-", 1)[0] \
                .split(".", 1)[0]
            try:
                return int(digits)
            except ValueError:
                return None
    if name.startswith(_GRAD_PREFIX + "e"):
        try:
            return int(name[len(_GRAD_PREFIX) + 1:].split("-", 1)[0])
        except ValueError:
            return None
    return None


def gc_epoch_files(dirname, keep_epoch, members=None, hb_grace=None,
                   now=None):
    """Epoch-scoped garbage collection: a long-lived elastic run must
    not grow its workdir without bound.  Drops membership-protocol
    files (``member-``/``join-``/``admit-``/``ready-``) and
    grad-exchange files from epochs before ``keep_epoch - 1`` — the
    current AND previous epoch are always retained, so nothing a
    straggler could still be reading disappears under it.  When
    ``members``/``hb_grace`` are given, also reclaims ``hb-*`` (and
    done-marker) files of ranks outside ``members`` whose last beat is
    more than ``hb_grace`` old — a pending joiner keeps beating, so its
    file survives.  Returns the removed names."""
    removed = []
    try:
        names = os.listdir(dirname)
    except OSError:
        return removed
    floor = int(keep_epoch) - 1
    now = time.time() if now is None else now
    members = set(int(m) for m in members) if members else set()
    for name in names:
        path = os.path.join(dirname, name)
        epoch = _protocol_epoch(name)
        if epoch is not None:
            if epoch >= floor:
                continue
        elif hb_grace is not None and name.startswith("hb-"):
            base = name[len("hb-"):].split(".", 1)[0]
            try:
                rank = int(base)
            except ValueError:
                continue
            if rank in members:
                continue
            try:
                if now - os.path.getmtime(path) <= hb_grace:
                    continue
            except OSError:
                continue
        else:
            continue
        try:
            os.unlink(path)
            removed.append(name)
        except OSError:
            pass
    return sorted(removed)


# ---------------------------------------------------------------------------
# program split at the optimizer boundary
# ---------------------------------------------------------------------------

SplitStep = collections.namedtuple(
    "SplitStep", ["head", "tail", "grad_names", "pre_scale",
                  "passthrough"])


def build_split(program):
    """Split a GradAllReduce-transpiled ``program`` into a *head* clone
    (forward + backward, collectives removed) and a *tail* clone
    (optimizer ops, reduced gradients fed by name).

    Follows the executor's ``_accum_partition`` contract: the cut is the
    first ``op_role == "optimize"`` op; non-persistable head outputs the
    tail reads (``passthrough``) ride the fetch/feed path, persistable
    ones flow through the scope.  ``grad_names`` are the (in-place)
    outputs of the removed ``c_allreduce_sum`` ops — exactly the
    gradients the optimizer consumes — and ``pre_scale`` is their
    recorded averaging factor.  Returns ``None`` when the program has no
    collectives (world 1 / "single" plan): run it whole.
    """
    block = program.global_block()
    ops = block.ops
    ar_ops = [op for op in ops if op.type == "c_allreduce_sum"]
    if not ar_ops:
        return None
    grad_names = []
    for op in ar_ops:
        for n in op.output_arg_names:
            if n not in grad_names:
                grad_names.append(n)
    pre_scale = float(ar_ops[0].attrs.get("pre_scale", 1.0))
    split = next((i for i, op in enumerate(ops)
                  if op.attrs.get("op_role") == "optimize"), len(ops))

    head_prog = program.clone()
    hb = head_prog.global_block()
    hb.ops = [op for op in hb.ops[:split]
              if op.type != "c_allreduce_sum"]
    head_prog._bump_version()

    tail_prog = program.clone()
    tb = tail_prog.global_block()
    tb.ops = list(tb.ops[split:])
    tail_prog._bump_version()

    head_written = set()
    for op in hb.ops:
        head_written.update(op.output_arg_names)
    passthrough = []
    for op in tb.ops:
        for n in op.input_arg_names:
            if not n or n in grad_names or n not in head_written \
                    or n in passthrough:
                continue
            v = block._find_var_recursive(n)
            if v is not None and v.persistable:
                continue  # scope carries it between the two runs
            passthrough.append(n)
    return SplitStep(head=head_prog, tail=tail_prog,
                     grad_names=grad_names, pre_scale=pre_scale,
                     passthrough=passthrough)


def reduce_gradients(per_member, scale):
    """Deterministic mirror of the on-disk exchange: float32 sum of each
    gradient over ``per_member`` (dicts in sorted-member order), scaled
    by ``scale``, cast back to the local dtype.  The in-process oracle
    and the distributed workers share this one reduction, so their
    trajectories can be compared within fp tolerance, not luck."""
    if not per_member:
        return {}
    out = {}
    for name, local in per_member[0].items():
        local = np.asarray(local)
        acc = np.zeros(local.shape, dtype=np.float32)
        for contrib in per_member:
            acc = acc + np.asarray(contrib[name], dtype=np.float32)
        out[name] = (acc * float(scale)).astype(local.dtype, copy=False)
    return out


def plan_world(program, startup_program, world, rank_index=0,
               batch_size=None):
    """Clone + re-plan ``program`` for ``world`` chips and prove the
    result safe: ``auto_transpile`` must return a deadlock-proved winner
    and the apply must introduce no new race signatures.  The elastic
    loop additionally pins the data-parallel family — whatever plan the
    planner prefers on paper, a shrunk *live* world must exchange
    gradients, so a "single" standin at world > 1 gets the
    GradAllReduce transpile at the full membership degree.

    Returns ``(train_prog, startup_prog, split, result, applied)``;
    ``split`` is None for world 1."""
    from ..parallel.planner import (apply_plan, auto_transpile,
                                    resolve_cluster_spec)
    from ..static_analysis.concurrency import (assert_no_new_races,
                                               race_signatures)
    from ..transpiler.collective import GradAllReduce

    world = int(world)
    prog = program.clone()
    startup = startup_program.clone() if startup_program is not None \
        else None
    spec = resolve_cluster_spec(chips=world)
    result = auto_transpile(prog, spec, startup_program=startup,
                            batch_size=batch_size)
    if not result.deadlock_free:
        raise ElasticError(
            "re-planned schedule for world=%d failed the deadlock "
            "proof: %s" % (world, result.plan.status))
    baseline = race_signatures(prog)
    applied = apply_plan(prog, result, startup_program=startup,
                         rank=rank_index)
    if world > 1 and not any(op.type == "c_allreduce_sum"
                             for op in prog.global_block().ops):
        GradAllReduce().transpile(program=prog, startup_program=startup,
                                  rank=rank_index, nranks=world)
    assert_no_new_races(prog, baseline,
                        "elastic re-plan (world=%d)" % world)
    return prog, startup, build_split(prog), result, applied


# ---------------------------------------------------------------------------
# file-rendezvous gradient exchange
# ---------------------------------------------------------------------------

def _grad_fname(epoch, step, rank):
    return "%se%04d-s%08d-r%d.npz" % (_GRAD_PREFIX, int(epoch),
                                      int(step), int(rank))


class GradExchange:
    """Deterministic all-reduce through a shared directory.

    Each member atomically publishes its local gradients for
    ``(epoch, step)`` and assembles the reduction from every member's
    file in sorted-member order (:func:`reduce_gradients`).  The wait
    for a peer's file IS the rendezvous barrier and the failure
    detector: a peer whose heartbeat goes stale, or that stays silent
    past ``wedge_timeout`` while still beating (alive but stuck), is
    reported as :class:`WorkerLostError` — the verdict the elastic loop
    recovers from.  Files from ``step - 2`` are reclaimed on each
    publish (every member passing the ``step - 1`` rendezvous proves
    they were consumed)."""

    def __init__(self, dirname, rank, members, monitor,
                 wedge_timeout=60.0, poll=0.02):
        self.dirname = dirname
        self.rank = int(rank)
        self.members = sorted(int(m) for m in members)
        self.monitor = monitor
        self.wedge_timeout = float(wedge_timeout)
        self.poll = float(poll)
        os.makedirs(dirname, exist_ok=True)

    def _publish(self, epoch, step, arrays):
        final = os.path.join(self.dirname,
                             _grad_fname(epoch, step, self.rank))
        tmp = "%s.tmp-%d" % (final, os.getpid())
        payload = {n: np.asarray(v) for n, v in arrays.items()}
        # traceparent rides in-band so a peer can link its exchange
        # span to ours; stripped before reduction (reduce_gradients
        # never sees it)
        tp = _tr.current_traceparent()
        if tp:
            payload["__traceparent__"] = np.asarray(tp)
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, final)
        old = os.path.join(self.dirname,
                           _grad_fname(epoch, step - 2, self.rank))
        if step >= 2 and os.path.exists(old):
            try:
                os.unlink(old)
            except OSError:
                pass

    def _wait_peer(self, epoch, step, peer, deadline):
        path = os.path.join(self.dirname, _grad_fname(epoch, step, peer))
        while not os.path.exists(path):
            stale = set(self.monitor.stale_ranks()) \
                & set(self.members)
            if stale:
                _record_lost(sorted(stale),
                             "heartbeat stale during gradient exchange "
                             "(epoch %d step %d)" % (epoch, step))
                raise WorkerLostError(
                    "worker rank(s) %s lost during gradient exchange at "
                    "step %d" % (sorted(stale), step),
                    ranks=sorted(stale))
            if time.time() > deadline:
                _record_lost([peer],
                             "wedged: heartbeat fresh but no gradients "
                             "for %.1fs (epoch %d step %d)"
                             % (self.wedge_timeout, epoch, step))
                raise WorkerLostError(
                    "worker rank %d wedged: alive but produced no "
                    "gradients for step %d within %.1fs"
                    % (peer, step, self.wedge_timeout), ranks=[peer])
            time.sleep(self.poll)
        return path

    def allreduce(self, epoch, step, grads, scale):
        """Publish ``grads`` and return the scaled sorted-member-order
        reduction over all members' contributions."""
        xspan = _tr.span("elastic.exchange", epoch=int(epoch),
                         step=int(step), members=len(self.members))
        with xspan:
            self._publish(epoch, step, grads)
            per_member = []
            peer_traces = {}
            deadline = time.time() + self.wedge_timeout
            for member in self.members:
                if member == self.rank:
                    per_member.append(grads)
                    continue
                path = self._wait_peer(epoch, step, member, deadline)
                with np.load(path) as z:
                    contrib = {n: z[n] for n in z.files
                               if n != "__traceparent__"}
                    if "__traceparent__" in z.files:
                        peer_traces[member] = str(z["__traceparent__"])
                per_member.append(contrib)
            if peer_traces and xspan.recording:
                xspan.set_attr("peer_traceparents", peer_traces)
            return reduce_gradients(per_member, scale)

    def sweep(self, keep_epoch):
        """Drop this rank's files from epochs before ``keep_epoch``
        (adopting a new membership obsoletes every older rendezvous)."""
        prefix = "%se" % _GRAD_PREFIX
        suffix = "-r%d.npz" % self.rank
        try:
            names = os.listdir(self.dirname)
        except OSError:
            return
        for name in names:
            if not (name.startswith(prefix) and name.endswith(suffix)):
                continue
            try:
                epoch = int(name[len(prefix):].split("-", 1)[0])
            except ValueError:
                continue
            if epoch < keep_epoch:
                try:
                    os.unlink(os.path.join(self.dirname, name))
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# the elastic trainer
# ---------------------------------------------------------------------------

class ElasticTrainer:
    """Own the train loop so recovery can rewind it.

    ``run(total_steps, make_feed)`` executes the split step —
    head (forward+backward) → file all-reduce → tail (optimizer) —
    checkpointing from the leader with the membership topology stamped
    into the manifest.  A :class:`WorkerLostError` anywhere in the step
    triggers the four-layer recovery *in this process*; the step that
    was interrupted re-runs under the new world.

    ``make_feed(step, index, world)`` receives the member's POSITION in
    the sorted membership, not its original rank: a constant global
    batch sliced by index keeps the global gradient identical across
    world sizes (equal slices assumed), which is what makes the
    shrunk-world oracle comparison in ``tools/chaos --elastic`` exact
    up to fp reassociation.
    """

    def __init__(self, program, startup_program, executor, rank, world,
                 workdir, fetch_list=(), batch_size=None, ckpt_every=1,
                 retain=None, hb_interval=0.25, stale_timeout=3.0,
                 wedge_timeout=60.0, state=None, warmup_timeout=120.0,
                 join_timeout=300.0):
        self.base_program = program
        self.base_startup = startup_program
        self.exe = executor
        self.rank = int(rank)
        self.workdir = workdir
        self.hb_dir = os.path.join(workdir, "hb")
        self.exchange_dir = os.path.join(workdir, "exchange")
        self.ckpt_dir = os.path.join(workdir, "ckpt")
        self.fetch_list = [getattr(v, "name", v) for v in fetch_list]
        self.batch_size = batch_size
        self.ckpt_every = max(int(ckpt_every), 1)
        self.retain = retain
        self.hb_interval = float(hb_interval)
        self.stale_timeout = float(stale_timeout)
        self.wedge_timeout = float(wedge_timeout)
        self.warmup_timeout = float(warmup_timeout)
        self.join_timeout = float(join_timeout)
        self.state = dict(state or {})

        self.epoch = 0
        self.members = list(range(int(world)))
        self.step = 0
        self.train_prog = None
        self.split = None
        self.zero1 = False
        self._hb = None
        self._monitor = None
        self._exchange = None
        self._recovering_since = None
        self._rejoining_since = None
        self._admission = None
        self._pending_member = None
        self._total_steps = None
        for d in (self.hb_dir, self.exchange_dir, self.ckpt_dir):
            os.makedirs(d, exist_ok=True)

    # -- membership-derived views -------------------------------------

    @property
    def world(self):
        return len(self.members)

    @property
    def index(self):
        return self.members.index(self.rank)

    def _is_leader(self):
        return self.rank == min(self.members)

    def _topology(self):
        return {"world": self.world, "zero1": bool(self.zero1)}

    def _adopt_membership(self, membership, keep_epoch=None):
        """Install an agreed membership: peers list, watchdog, exchange,
        and the fleet env contract (``PADDLE_TRAINER_ID`` /
        ``PADDLE_TRAINERS_NUM``) that role makers and ``_is_primary``
        read — after a leader loss the new leader must also *look*
        primary to every downstream layer.

        ``keep_epoch`` widens the sweep/GC retention floor: a grow
        transition keeps the outgoing epoch's grad files on disk because
        a peer one boundary behind may still be reading them (the shrink
        path has no such reader — every survivor abandoned the old
        rendezvous)."""
        self.epoch = membership.epoch
        self.members = list(membership.members)
        if self.rank not in self.members:
            raise ElasticEvictedError(
                "rank %d is not part of membership epoch %d %s — "
                "exiting with ELASTIC_EVICTED_EXIT_CODE"
                % (self.rank, self.epoch, self.members))
        os.environ["PADDLE_TRAINER_ID"] = str(self.index)
        os.environ["PADDLE_TRAINERS_NUM"] = str(self.world)
        peers = [m for m in self.members if m != self.rank]
        self._monitor = HeartbeatMonitor(
            self.hb_dir, peers, timeout=self.stale_timeout,
            interval=self.hb_interval, boot_grace=self.wedge_timeout)
        self._exchange = GradExchange(
            self.exchange_dir, self.rank, self.members, self._monitor,
            wedge_timeout=self.wedge_timeout)
        keep = self.epoch if keep_epoch is None else int(keep_epoch)
        self._exchange.sweep(keep)
        if self._is_leader():
            # epoch-scoped GC (current + previous epoch retained); its
            # floor is already one epoch behind ``keep_epoch``, so the
            # grow transition's outgoing-epoch grad files survive it
            # either way.  The hb grace is generous so only long-gone
            # ranks lose their beat files — pending joiners keep
            # beating and are safe
            gc_epoch_files(
                self.hb_dir, self.epoch, members=self.members,
                hb_grace=max(self.wedge_timeout,
                             4.0 * self.stale_timeout))
            gc_epoch_files(self.exchange_dir, self.epoch)
        from ..observability import runtime as _obs

        _obs.set_elastic_state(self.epoch, self.world)

    # -- planning / restore --------------------------------------------

    def _plan(self):
        t0 = time.perf_counter()
        old_world = self.world if self.train_prog is not None else None
        with _tr.span("elastic.replan", epoch=self.epoch,
                      world=self.world):
            (self.train_prog, startup, self.split, result,
             applied) = plan_world(self.base_program, self.base_startup,
                                   self.world, rank_index=self.index,
                                   batch_size=self.batch_size)
        self.zero1 = bool(getattr(self.train_prog,
                                  "_shard_optimizer_state", False))
        if old_world is not None:
            from ..observability import runtime as _obs

            _obs.record_replan(
                self.epoch, old_world, self.world, applied.describe(),
                (time.perf_counter() - t0) * 1000.0)
        return startup

    def _topology_compatible(self, recorded):
        expected = self._topology()
        return not any(k in recorded and recorded[k] != expected[k]
                       for k in expected)

    def _restore(self, recovery, leader=None, require=False):
        """Load the newest checkpoint at the CURRENT topology.  The
        leader reshards a mismatched latest version first; followers
        wait for the resharded manifest to land rather than loading a
        stale layout or silently falling back to an older version.

        ``leader`` overrides who owns the reshard: during a grow
        transition the OLD leader holds the fresh checkpoint, and an
        admitted joiner with a lower rank than every member must not
        grab the reshard it cannot yet serve.  ``require`` makes an
        empty checkpoint root a wait, not a pass — a joiner has no
        in-memory state to fall back on."""
        topo = self._topology()
        if leader is None:
            leader = self._is_leader()
        if leader:
            versions = _ckpt.list_checkpoints(self.ckpt_dir)
            if versions:
                _step, path = versions[0]
                recorded = _ckpt.read_topology(path)
                if recorded is not None \
                        and not self._topology_compatible(recorded):
                    from .reshard import reshard_checkpoint

                    reshard_checkpoint(path, topo)
        else:
            self._await_resharded(recovery, require=require)
        info = _ckpt.try_load_latest_checkpoint(
            self.exe, self.ckpt_dir, main_program=self.train_prog,
            expected_topology=topo)
        if info is not None:
            self.step = int(info.state.get("step", info.step)) + 1
            self.state.update(info.state.get("extra", {}))
        elif not recovery:
            self.step = 0
        # on recovery with no checkpoint at all, every survivor keeps
        # its in-memory state: the tail applied identical reduced
        # gradients everywhere, so replicated state is still consistent
        # and self.step already points at the interrupted step
        return info

    def _await_resharded(self, recovery, none_grace=2.0,
                         require=False):
        """Follower side of the reshard rendezvous: poll until the
        newest version's recorded topology fits this world.  A brief
        empty-listing window is tolerated (the leader's save-aside
        replacement renames the dir out and back); a persistent empty
        root means there is nothing to restore — unless ``require``
        (the joiner path), where only a compatible checkpoint counts."""
        deadline = time.time() + self.wedge_timeout
        none_since = None
        while True:
            versions = _ckpt.list_checkpoints(self.ckpt_dir)
            if versions:
                none_since = None
                try:
                    recorded = _ckpt.read_topology(versions[0][1])
                except _ckpt.CorruptCheckpointError:
                    recorded = None  # racing the replacement rename
                if recorded is None and require:
                    recorded = {"world": -1}  # keep waiting
                if recorded is None \
                        or self._topology_compatible(recorded):
                    return
            elif not require:
                if not recovery:
                    return  # fresh start: nothing will appear
                if none_since is None:
                    none_since = time.time()
                elif time.time() - none_since > none_grace:
                    return
            if time.time() > deadline:
                raise ElasticError(
                    "timed out after %.1fs waiting for the leader to "
                    "reshard the checkpoint for %s"
                    % (self.wedge_timeout, self._topology()))
            time.sleep(0.05)

    # -- the step -------------------------------------------------------

    def _run_step(self, make_feed):
        step = self.step
        _faults.set_step(step)
        self._hb.beat()
        feed = make_feed(step, self.index, self.world)
        if self.split is None:
            return self.exe.run(program=self.train_prog, feed=feed,
                                fetch_list=list(self.fetch_list))
        sp = self.split
        head_fetch = (list(self.fetch_list) + sp.grad_names
                      + sp.passthrough)
        out = self.exe.run(program=sp.head, feed=feed,
                           fetch_list=head_fetch)
        nf = len(self.fetch_list)
        ng = len(sp.grad_names)
        fetches = out[:nf]
        grads = dict(zip(sp.grad_names, out[nf:nf + ng]))
        passthrough = dict(zip(sp.passthrough, out[nf + ng:]))
        reduced = self._exchange.allreduce(self.epoch, step, grads,
                                           sp.pre_scale)
        tail_feed = dict(passthrough)
        tail_feed.update(reduced)
        self.exe.run(program=sp.tail, feed=tail_feed, fetch_list=[])
        return fetches

    def _maybe_checkpoint(self):
        if not self._is_leader() \
                or (self.step + 1) % self.ckpt_every != 0:
            return
        _ckpt.save_checkpoint(
            self.exe, self.ckpt_dir, main_program=self.train_prog,
            step=self.step,
            state={"step": self.step, "extra": self.state},
            retain=self.retain, all_ranks=True,
            topology=self._topology())

    # -- scale-up: admission (leader side) ------------------------------

    def _maybe_admit(self):
        """Leader-side admission state machine, run at every healthy
        step boundary.  Phase 1 turns fresh join requests into a
        write-once admit record; phase 2 watches the admitted joiners'
        warm-up, drops any that die or wedge (heartbeat staleness /
        warm-up budget), and finalizes ``member-<epoch+1>`` with a
        ``start_step`` two boundaries out — the lockstep exchange makes
        that horizon race-free.  The fleet keeps stepping at the old
        epoch throughout."""
        if not join_enabled() or not self._is_leader() \
                or self._pending_member is not None:
            return
        total = self._total_steps
        if self._admission is None:
            if total is not None and self.step + 4 >= total:
                return  # no headroom left for warm-up + transition
            joiners = [r for r in pending_joins(
                self.hb_dir, self.epoch,
                stale_timeout=max(self.stale_timeout,
                                  4.0 * self.hb_interval))
                if r not in self.members]
            if not joiners:
                return
            from ..observability import runtime as _obs

            got = _write_once(
                _admit_path(self.hb_dir, self.epoch + 1), {
                    "schema": 1, "epoch": self.epoch + 1,
                    "members": list(self.members), "joiners": joiners,
                    "writer": self.rank, "ts": time.time(),
                    "traceparent": _tr.current_traceparent(),
                })
            self._admission = {
                "epoch": int(got["epoch"]),
                "joiners": [int(r) for r in got["joiners"]],
                "deadline": time.time() + self.warmup_timeout,
            }
            _obs.set_elastic_state(
                self.epoch, self.world,
                pending=len(self._admission["joiners"]))
            _obs.record_join_admitted(self._admission["epoch"],
                                      self._admission["joiners"])
            return
        adm = self._admission
        ready = [r for r in adm["joiners"] if os.path.exists(
            _ready_path(self.hb_dir, adm["epoch"], r))]
        waiting = [r for r in adm["joiners"] if r not in ready]
        if waiting:
            now = time.time()
            dead = [r for r in waiting
                    if (lambda hb: hb is None
                        or now - hb["mtime"] > self.stale_timeout)(
                        read_heartbeat(self.hb_dir, r))]
            if now > adm["deadline"]:
                dead = list(waiting)  # warm-up budget exhausted
            if len(dead) < len(waiting):
                return  # still warming up: keep the old epoch stepping
            if dead:
                _record_lost(sorted(dead),
                             "joiner died or wedged mid-warm-up "
                             "(admission epoch %d)" % adm["epoch"])
        if total is not None and self.step + 2 >= total:
            return  # too late to transition before the run ends
        members = sorted(set(self.members) | set(ready))
        got = _write_once(_member_path(self.hb_dir, adm["epoch"]), {
            "schema": 1, "epoch": adm["epoch"], "members": members,
            "world": len(members), "lost": [], "reason": "grow",
            "joined": ready, "writer": self.rank,
            "start_step": self.step + 2, "ts": time.time(),
            "traceparent": _tr.current_traceparent(),
        })
        self._admission = None
        self._pending_member = got
        from ..observability import runtime as _obs

        _obs.set_elastic_state(self.epoch, self.world, pending=0)

    # -- scale-up: the grown-epoch transition (every member) ------------

    def _maybe_transition(self):
        """Adopt a finalized grown membership exactly at its
        ``start_step`` boundary.  The record was written at the
        leader's boundary ``start_step - 2`` and the exchange is
        lockstep, so every member observes it at least one boundary
        before the transition — no member can run a step under the old
        epoch that a peer already ran under the new one."""
        if self._pending_member is None:
            path = _member_path(self.hb_dir, self.epoch + 1)
            if not os.path.exists(path):
                return
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                return  # racing the writer's link; retry next boundary
            if rec.get("start_step") is None:
                return  # a shrink record: reached via WorkerLostError
            self._pending_member = rec
        if self.step < int(self._pending_member["start_step"]):
            return
        self._transition(self._pending_member)

    def _transition(self, rec):
        t0 = time.perf_counter()
        old_members = list(self.members)
        was_leader = self._is_leader()
        membership = _membership_from_record(rec)
        grew = list(membership.members) != old_members
        with _tr.span("elastic.grow", epoch=membership.epoch,
                      world=membership.world):
            if grew and was_leader:
                # the joiners restore from a checkpoint of the state
                # entering start_step — force one if the cadence missed
                self._checkpoint_now()
            self._adopt_membership(membership,
                                   keep_epoch=membership.epoch - 1)
            self._pending_member = None
            self._admission = None
            if not grew:
                return  # every admitted joiner died warming up:
                        # epoch bump only, keep stepping
            self._plan()
            with _tr.span("elastic.restore"):
                self._restore(recovery=True, leader=was_leader)
        self._recovering_since = t0
        _faults.set_step(self.step)

    def _checkpoint_now(self):
        versions = _ckpt.list_checkpoints(self.ckpt_dir)
        if versions and int(versions[0][0]) >= self.step - 1:
            return
        _ckpt.save_checkpoint(
            self.exe, self.ckpt_dir, main_program=self.train_prog,
            step=self.step - 1,
            state={"step": self.step - 1, "extra": self.state},
            retain=self.retain, all_ranks=True,
            topology=self._topology())

    # -- scale-up: the joiner side --------------------------------------

    def _read_admit(self, epoch):
        try:
            with open(_admit_path(self.hb_dir, epoch)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _await_member_record(self, target, deadline):
        while True:
            epoch, rec = latest_epoch(self.hb_dir)
            if epoch is not None and epoch >= target and rec is not None:
                return rec
            if time.time() > deadline:
                raise ElasticError(
                    "membership epoch %d did not land within the join "
                    "timeout" % target)
            time.sleep(0.05)

    def _dry_run(self, make_feed):
        """Compile both halves of the split step by running them once on
        real feed shapes — the warm-up contract: all jit cost is paid
        BEFORE the ready ack, so the fleet's first grown step is not a
        compile stall.  Parameter values are scratch; the restore that
        follows admission overwrites them."""
        feed = make_feed(self.step, self.index, self.world)
        if self.split is None:
            self.exe.run(program=self.train_prog, feed=feed,
                         fetch_list=list(self.fetch_list))
            return
        sp = self.split
        out = self.exe.run(program=sp.head, feed=feed,
                           fetch_list=(list(self.fetch_list)
                                       + sp.grad_names
                                       + sp.passthrough))
        nf = len(self.fetch_list)
        ng = len(sp.grad_names)
        grads = dict(zip(sp.grad_names, out[nf:nf + ng]))
        passthrough = dict(zip(sp.passthrough, out[nf + ng:]))
        reduced = reduce_gradients([grads] * self.world, sp.pre_scale)
        tail_feed = dict(passthrough)
        tail_feed.update(reduced)
        self.exe.run(program=sp.tail, feed=tail_feed, fetch_list=[])

    def _join_fleet(self, make_feed):
        """Joiner entry: post the write-once join request against the
        newest observed epoch, heartbeat while waiting, warm up on
        admission, and only ack ready once compiled.  Re-posts when the
        fleet's epoch moves under us (a concurrent shrink consumes the
        epoch we asked for) and retries when an admission round rolls
        forward without us."""
        from ..observability import runtime as _obs

        t0 = time.perf_counter()
        deadline = time.time() + self.join_timeout
        observed = None
        with _tr.span("elastic.join", rank=self.rank):
            while True:
                epoch, _rec = latest_epoch(self.hb_dir)
                epoch = 0 if epoch is None else epoch
                if observed is None or epoch > observed:
                    observed = epoch
                    request_join(self.hb_dir, self.rank, observed)
                    _obs.record_join_request(self.rank, observed)
                admit = self._read_admit(observed + 1)
                if admit is None or self.rank not in [
                        int(r) for r in admit.get("joiners", [])]:
                    if time.time() > deadline:
                        raise ElasticError(
                            "join request by rank %d was not admitted "
                            "within %.1fs"
                            % (self.rank, self.join_timeout))
                    time.sleep(0.05)
                    continue
                target = int(admit["epoch"])
                provisional = sorted(
                    set(int(m) for m in admit["members"])
                    | set(int(r) for r in admit["joiners"]))
                wt0 = time.perf_counter()
                with _tr.span("elastic.warmup", epoch=target,
                              world=len(provisional)):
                    self._adopt_membership(Membership(
                        epoch=target, members=provisional,
                        world=len(provisional), lost=[],
                        writer=int(admit.get("writer", -1)),
                        traceparent=admit.get("traceparent")))
                    startup = self._plan()
                    if startup is not None:
                        self.exe.run(program=startup)
                    self._dry_run(make_feed)
                    _write_once(
                        _ready_path(self.hb_dir, target, self.rank),
                        {"schema": 1, "rank": self.rank,
                         "epoch": target, "ts": time.time()})
                _obs.record_warmup(
                    self.rank, target,
                    (time.perf_counter() - wt0) * 1000.0)
                final = self._await_member_record(target, deadline)
                if self.rank not in [int(m) for m in final["members"]]:
                    continue  # round rolled forward without us: retry
                membership = _membership_from_record(final)
                replan = list(membership.members) != self.members
                self._adopt_membership(membership)
                if replan:
                    self._plan()  # a co-joiner was dropped mid-warm-up
                with _tr.span("elastic.restore"):
                    self._restore(recovery=True, leader=False,
                                  require=True)
                self.step = max(self.step,
                                int(final.get("start_step", 0)))
                break
        self._rejoining_since = t0
        _faults.set_step(self.step)

    # -- recovery -------------------------------------------------------

    def _recover(self, err):
        t0 = time.perf_counter()
        # a shrink consumes the next epoch: any in-flight admission or
        # pending grown membership is void, joiners re-request later
        self._admission = None
        self._pending_member = None
        lost = sorted(set(int(r) for r in err.ranks)
                      & set(self.members))
        if not lost:
            raise err  # a loss verdict naming no current member
        survivors = [m for m in self.members if m not in lost]
        if not survivors or self.rank not in survivors:
            raise ElasticEvictedError(
                "rank %d was declared lost (%s) — exiting"
                % (self.rank, err))
        with _tr.span("elastic.recover", epoch=self.epoch + 1,
                      lost=lost, survivors=len(survivors)):
            with _tr.span("elastic.agree"):
                membership = agree_membership(
                    self.hb_dir, self.rank, self.epoch + 1, survivors,
                    lost, reason=str(err),
                    stale_timeout=self.stale_timeout,
                    timeout=self.wedge_timeout)
            self._adopt_membership(membership)
            self._plan()
            with _tr.span("elastic.restore"):
                self._restore(recovery=True)
        self._recovering_since = t0
        _faults.set_step(self.step)

    def _after_step(self):
        if self._recovering_since is not None:
            from ..observability import runtime as _obs

            _obs.record_elastic_recovery(
                self.epoch, self.step, self.world,
                (time.perf_counter() - self._recovering_since)
                * 1000.0)
            self._recovering_since = None
        if self._rejoining_since is not None:
            from ..observability import runtime as _obs

            # join-request → first completed full-world step
            _obs.record_rejoin(
                self.epoch, self.step, self.world,
                (time.perf_counter() - self._rejoining_since)
                * 1000.0)
            self._rejoining_since = None

    # -- entry point ----------------------------------------------------

    def _publish_initial_membership(self):
        """First-wins publish of the boot epoch's record so a later
        joiner can discover the current membership from disk alone."""
        if os.path.exists(_member_path(self.hb_dir, self.epoch)):
            return
        _write_once(_member_path(self.hb_dir, self.epoch), {
            "schema": 1, "epoch": int(self.epoch),
            "members": list(self.members), "world": len(self.members),
            "lost": [], "reason": "boot", "writer": self.rank,
            "ts": time.time(),
            "traceparent": _tr.current_traceparent(),
        })

    def run(self, total_steps, make_feed, on_step=None, join=False):
        """Train ``total_steps`` steps, recovering from worker loss
        in-process.  ``on_step(step, fetches, trainer)`` observes each
        completed step.  With ``join=True`` this worker is not part of
        the boot membership: it requests admission, warms up, and
        enters the fleet at the agreed ``start_step``.  Returns the
        final step count."""
        membership = Membership(
            epoch=self.epoch, members=list(self.members),
            world=len(self.members), lost=[], writer=self.rank)
        self._total_steps = int(total_steps)
        self._hb = HeartbeatWriter(self.hb_dir, self.rank,
                                   interval=self.hb_interval).start()
        # the worker's root span: joins the drill/driver trace when
        # PADDLE_TPU_TRACEPARENT is in the env (the remote-parent
        # fallback), so one trace covers every rank through recovery.
        # Rank reaches this process as an argument, not env, and the
        # fleet env contract is only written at membership adoption —
        # stamp spans with the stable elastic rank explicitly (the
        # post-recovery index would mislabel survivors of a leader
        # loss).
        if _tr.tracing_enabled():
            _tr.set_rank(self.rank)
        with _tr.span("elastic.worker", rank=self.rank,
                      world=len(self.members)):
            try:
                if join:
                    self._join_fleet(make_feed)
                else:
                    self._publish_initial_membership()
                    self._adopt_membership(membership)
                    startup = self._plan()
                    if startup is not None:
                        self.exe.run(program=startup)
                    self._restore(recovery=False)
                while self.step < int(total_steps):
                    self._maybe_transition()
                    self._maybe_admit()
                    try:
                        with _tr.span("elastic.step", step=self.step,
                                      epoch=self.epoch):
                            fetches = self._run_step(make_feed)
                    except WorkerLostError as e:
                        self._recover(e)
                        continue
                    self._after_step()
                    self._maybe_checkpoint()
                    if on_step is not None:
                        on_step(self.step, fetches, self)
                    self.step += 1
                return self.step
            finally:
                self._hb.stop()
