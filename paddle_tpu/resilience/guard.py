"""NaN/Inf step-guard: skip the parameter update on a non-finite step.

Dynamic-loss-scaling semantics without the scaling: the jitted train step
computes one all-finite flag over every produced gradient plus every
inexact fetch (the loss), and every scope-state update is routed through
``where(finite, new, old)`` — a non-finite step leaves params, optimizer
moments and in-graph counters bit-identical to the step before, exactly
as if the step had not run.  The flag rides the fetch list back to the
host, where :func:`record_step` keeps the structured skip counter and
emits a :class:`NonFiniteStepWarning`.

Enable with env ``PADDLE_TPU_NAN_GUARD=1`` or per-program
``program._nan_guard = True``; runs with the guard off behave (and
compile) exactly as before.  ``PADDLE_TPU_NAN_GUARD_MAX_SKIPS`` (default
25) bounds *consecutive* skipped steps — a run whose every step is
non-finite has diverged and must crash loudly, not spin.
"""

import os
import warnings

__all__ = ["NonFiniteStepWarning", "GuardStats", "stats", "guard_enabled",
           "record_step", "max_consecutive_skips"]


class NonFiniteStepWarning(UserWarning):
    """A training step produced non-finite loss/gradients and its
    parameter update was skipped."""


class GuardStats:
    """Structured skip counter (process-wide; ``stats`` is the
    singleton)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.total_steps = 0
        self.skipped_steps = 0
        self.consecutive_skips = 0
        self.last_skipped_step = None

    def as_dict(self):
        return {
            "total_steps": self.total_steps,
            "skipped_steps": self.skipped_steps,
            "consecutive_skips": self.consecutive_skips,
            "last_skipped_step": self.last_skipped_step,
        }

    def __repr__(self):
        return "<GuardStats %s>" % self.as_dict()


stats = GuardStats()


def _truthy(val):
    return str(val).strip().lower() not in ("", "0", "false", "off", "no")


def guard_enabled(program=None):
    """Is the finite step-guard on for this run?  Env wins; a program
    can opt in via ``program._nan_guard = True``."""
    env = os.environ.get("PADDLE_TPU_NAN_GUARD")
    if env is not None:
        return _truthy(env)
    return bool(getattr(program, "_nan_guard", False))


def max_consecutive_skips():
    try:
        return int(os.environ.get("PADDLE_TPU_NAN_GUARD_MAX_SKIPS", "25"))
    except ValueError:
        return 25


def record_step(finite, step=None):
    """Host-side bookkeeping for one guarded step.  Returns ``finite``;
    raises ``RuntimeError`` once ``max_consecutive_skips`` consecutive
    steps were non-finite (the run has diverged — backoff cannot fix
    arithmetic)."""
    from ..observability import runtime as _obs

    finite = bool(finite)
    stats.total_steps += 1
    _obs.record_guard_step(finite)
    if finite:
        stats.consecutive_skips = 0
        return True
    stats.skipped_steps += 1
    stats.consecutive_skips += 1
    stats.last_skipped_step = step
    _obs.record_guard_skip(step, stats.consecutive_skips)
    warnings.warn(
        "non-finite loss/gradients at step %s — parameter update skipped "
        "(%d/%d steps skipped so far)"
        % (step, stats.skipped_steps, stats.total_steps),
        NonFiniteStepWarning, stacklevel=3)
    limit = max_consecutive_skips()
    if limit > 0 and stats.consecutive_skips >= limit:
        from ..observability import tracing as _tracing

        _tracing.flight_dump(
            "guard-abort: %d consecutive non-finite steps at step %s"
            % (stats.consecutive_skips, step))
        raise RuntimeError(
            "finite step-guard skipped %d consecutive steps (limit %d, "
            "env PADDLE_TPU_NAN_GUARD_MAX_SKIPS) — the run has diverged; "
            "lower the learning rate or restore an earlier checkpoint"
            % (stats.consecutive_skips, limit))
    return False
