"""Worker liveness: heartbeats, hang watchdogs, cluster supervision.

The failure this layer exists for: one worker of a gloo/ICI cluster dies
(OOM kill, preemption, injected ``worker_kill``) and every *surviving*
worker blocks forever inside its next collective — the run doesn't crash,
it silently stops.  Three cooperating pieces bound that hang:

* :class:`HeartbeatWriter` — each worker touches ``hb-<rank>`` in a
  shared directory every ``interval`` seconds from a daemon thread;
* :class:`HeartbeatMonitor` — each worker (and/or the parent) watches the
  peers' files; a rank whose heartbeat goes stale past ``timeout`` is
  declared lost.  The background form (``start()``) default-exits the
  process with :data:`LOST_EXIT_CODE` so a worker wedged in a collective
  dies promptly and visibly instead of hanging;
* :func:`wait_cluster` — the parent-side supervisor: polls worker
  subprocesses and converts "one died while others still run" or "nobody
  finished before the deadline" into :class:`WorkerLostError` within a
  bounded time, killing the survivors so the job can restart cleanly.

File mtimes, not sockets: localhost multiprocess clusters (the test
harness) and NFS-backed real ones both get this for free, and a
heartbeat writer that is itself wedged cannot lie.
"""

import json
import os
import sys
import threading
import time

__all__ = ["WorkerLostError", "HeartbeatWriter", "HeartbeatMonitor",
           "wait_cluster", "read_heartbeat", "LOST_EXIT_CODE"]

#: exit status a worker uses when its peer-loss watchdog trips
LOST_EXIT_CODE = 44


class WorkerLostError(RuntimeError):
    """A cluster worker died or went silent.  ``.ranks`` names the lost
    ranks (when known), ``.returncodes`` the observed exit codes."""

    def __init__(self, message, ranks=(), returncodes=()):
        super().__init__(message)
        self.ranks = tuple(ranks)
        self.returncodes = tuple(returncodes)


def _record_lost(ranks, reason):
    """Journal + count a worker-loss verdict (urgent-flushed — the
    default on_lost handler ``os._exit``\\ s right after)."""
    try:
        from ..observability import runtime as _obs

        _obs.record_missed_beat(ranks)
        _obs.record_worker_lost(ranks, reason=reason)
    except Exception:  # noqa: BLE001 - telemetry never blocks the exit
        pass


def _hb_path(dirname, rank):
    return os.path.join(dirname, "hb-%d" % rank)


def _done_path(dirname, rank):
    return _hb_path(dirname, rank) + ".done"


def read_heartbeat(dirname, rank):
    """Parse one rank's heartbeat file: ``{"t", "rank", and — when the
    telemetry layer has seen a step — "step", "step_ms", "step_ts"}``,
    plus ``"mtime"`` (what staleness is judged on).  Returns None when
    the file is absent.  Tolerates the pre-telemetry plain-float
    payload and torn writes (mtime still counts as a beat)."""
    path = _hb_path(dirname, rank)
    try:
        mtime = os.path.getmtime(path)
        with open(path) as f:
            raw = f.read().strip()
    except OSError:
        return None
    out = {"rank": int(rank), "mtime": mtime}
    try:
        payload = json.loads(raw)
        if isinstance(payload, dict):
            out.update(payload)
        else:
            out["t"] = float(payload)
    except (ValueError, TypeError):
        try:
            out["t"] = float(raw)
        except (ValueError, TypeError):
            pass
    return out


class HeartbeatWriter:
    """Touch ``hb-<rank>`` every ``interval`` seconds (daemon thread)."""

    def __init__(self, dirname, rank, interval=0.5):
        self.dirname = dirname
        self.rank = int(rank)
        self.interval = float(interval)
        self._stop = threading.Event()
        self._thread = None
        os.makedirs(dirname, exist_ok=True)

    def beat(self):
        """One heartbeat now (atomic create-or-touch; no fsync — a beat
        is cheap and its loss is one interval, not corruption).

        The payload carries the newest step number + step latency from
        the telemetry layer, so ``tools/monitor`` can tell a
        wedged-but-alive rank (fresh beats, step frozen) from a healthy
        one.  Staleness detection stays mtime-based — a reader that
        ignores the content loses nothing."""
        from .atomic import atomic_write

        payload = {"t": time.time(), "rank": self.rank}
        try:
            from ..observability import runtime as _obs

            info = _obs.last_step_info()
            if info.get("step") is not None:
                payload["step"] = info["step"]
                payload["step_ms"] = round(info["step_ms"], 3)
                payload["step_ts"] = info["ts"]
        except Exception:  # noqa: BLE001 - a beat must never fail
            pass
        atomic_write(_hb_path(self.dirname, self.rank),
                     lambda f: f.write(json.dumps(payload) + "\n"),
                     fsync=False, text=True)

    def start(self):
        self.beat()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="paddle_tpu-heartbeat-%d" % self.rank)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.beat()
            except OSError:
                pass  # shared fs hiccup: skip the beat, not the thread

    def stop(self):
        """Clean shutdown: leave a ``.done`` marker so peers' monitors
        know this rank *finished* rather than died — a worker still
        wrapping up (final checkpoint) must not be declared lost just
        because a faster peer exited first."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval + 1.0)
        try:
            with open(_done_path(self.dirname, self.rank), "w") as f:
                f.write("%f\n" % time.time())
        except OSError:
            pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class HeartbeatMonitor:
    """Watch peer heartbeats; declare a rank lost when its file goes
    ``timeout`` seconds stale (or never appears within ``boot_grace``)."""

    def __init__(self, dirname, ranks, timeout=10.0, interval=0.5,
                 boot_grace=60.0):
        self.dirname = dirname
        self.ranks = [int(r) for r in ranks]
        self.timeout = float(timeout)
        self.interval = float(interval)
        self.boot_grace = float(boot_grace)
        self._born = time.time()
        self._seen = set()  # ranks whose live heartbeat we've observed
        self._stop = threading.Event()
        self._thread = None

    def stale_ranks(self, now=None):
        now = time.time() if now is None else now
        stale = []
        for rank in self.ranks:
            try:
                done_m = os.path.getmtime(_done_path(self.dirname, rank))
            except OSError:
                done_m = None
            # a clean-shutdown marker from THIS incarnation: the peer
            # finished, it didn't die (pre-birth markers are leftovers)
            if done_m is not None and done_m >= self._born - self.timeout:
                continue
            try:
                mtime = os.path.getmtime(_hb_path(self.dirname, rank))
            except OSError:
                mtime = None
            # a beat within one timeout of our birth counts as live even
            # if it predates us (the peer may have booted first); older
            # pre-birth files are leftovers from an earlier incarnation
            if mtime is None or (mtime < self._born - self.timeout
                                 and rank not in self._seen):
                # the peer hasn't booted yet — only fatal once the boot
                # grace runs out
                if now - self._born > self.boot_grace:
                    stale.append(rank)
                continue
            self._seen.add(rank)
            if now - mtime > self.timeout:
                stale.append(rank)
        return stale

    def progress_of(self, rank):
        """The rank's parsed heartbeat payload (see
        :func:`read_heartbeat`), or None."""
        return read_heartbeat(self.dirname, rank)

    def check(self):
        """Raise :class:`WorkerLostError` if any watched rank is stale."""
        stale = self.stale_ranks()
        if stale:
            _record_lost(stale, "heartbeat stale > %.1fs" % self.timeout)
            raise WorkerLostError(
                "worker rank(s) %s heartbeat stale for > %.1fs (dir %s)"
                % (stale, self.timeout, self.dirname), ranks=stale)
        return True

    def start(self, on_lost=None):
        """Background watch.  Default ``on_lost`` prints WORKER_LOST and
        hard-exits with :data:`LOST_EXIT_CODE` — the surviving worker is
        very likely wedged inside a collective whose peer is gone, and a
        prompt visible death is the recoverable outcome."""

        def _default_on_lost(ranks):
            print("WORKER_LOST ranks=%s (heartbeat stale > %.1fs)"
                  % (ranks, self.timeout), file=sys.stderr, flush=True)
            os._exit(LOST_EXIT_CODE)

        handler = on_lost or _default_on_lost

        def _loop():
            while not self._stop.wait(self.interval):
                stale = self.stale_ranks()
                if stale:
                    _record_lost(stale,
                                 "heartbeat stale > %.1fs" % self.timeout)
                    handler(stale)
                    return

        self._thread = threading.Thread(
            target=_loop, daemon=True, name="paddle_tpu-hb-monitor")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval + 1.0)


def wait_cluster(procs, timeout=None, poll=0.25, kill_on_failure=True):
    """Supervise cluster worker subprocesses (``subprocess.Popen``-like:
    ``poll()``/``kill()``).  Returns the list of return codes once ALL
    exit zero.  Raises :class:`WorkerLostError` within ``poll`` seconds
    of any worker dying nonzero while peers still run (the survivors are
    killed first when ``kill_on_failure``), or when ``timeout`` expires
    with workers still running — a bounded answer instead of a silent
    collective hang."""
    deadline = None if timeout is None else time.time() + float(timeout)
    while True:
        codes = [p.poll() for p in procs]
        bad = [(i, c) for i, c in enumerate(codes)
               if c is not None and c != 0]
        if bad:
            if kill_on_failure:
                for p, c in zip(procs, codes):
                    if c is None:
                        p.kill()
            ranks = [i for i, _ in bad]
            _record_lost(ranks, "exited with code(s) %s"
                         % [c for _, c in bad])
            raise WorkerLostError(
                "cluster worker(s) %s exited with code(s) %s"
                % (ranks, [c for _, c in bad]),
                ranks=ranks, returncodes=[c for _, c in bad])
        if all(c == 0 for c in codes):
            return codes
        if deadline is not None and time.time() > deadline:
            hung = [i for i, c in enumerate(codes) if c is None]
            if kill_on_failure:
                for p, c in zip(procs, codes):
                    if c is None:
                        p.kill()
            _record_lost(hung, "timeout after %.1fs" % float(timeout))
            raise WorkerLostError(
                "cluster worker(s) %s still running after %.1fs timeout "
                "(likely hung in a collective)" % (hung, float(timeout)),
                ranks=hung)
        time.sleep(poll)
