"""Fault-tolerant training runtime (ISSUE 2).

The reference framework's fault story — checkpoint_utils, ``PADDLE_*``
env-driven trainer restarts — reproduced TPU-native and made testable:

* :mod:`~paddle_tpu.resilience.faults` — deterministic, seeded fault
  injection (env ``PADDLE_TPU_FAULT_SPEC``): NaN/Inf into chosen
  gradients, transient checkpoint/compile/barrier failures, worker
  kill/hang;
* :mod:`~paddle_tpu.resilience.checkpoint` — atomic + versioned
  checkpoints (stage → checksum manifest → rename), retain-last-K, and
  :func:`try_load_latest_checkpoint` auto-resume that skips torn or
  tampered versions;
* :mod:`~paddle_tpu.resilience.guard` — the NaN/Inf step-guard: a fetched
  all-finite flag gates every state update in-graph, so a non-finite step
  is skipped (dynamic-loss-scaling semantics) and counted;
* :mod:`~paddle_tpu.resilience.retry` — jittered exponential backoff +
  timeouts around checkpoint I/O, executor compilation and fleet
  barriers;
* :mod:`~paddle_tpu.resilience.watchdog` — heartbeats and cluster
  supervision turning a dead peer into a bounded
  :class:`WorkerLostError` instead of a collective hang;
* :mod:`~paddle_tpu.resilience.elastic` — the ISSUE-12 recovery loop:
  on worker loss, survivors agree on a shrunk membership, re-plan and
  re-prove the schedule, reshard the checkpoint, and resume in-process
  (no restart, no lost hardware) — plus the ISSUE-17 upward half: a
  returning worker posts a write-once join request, warms up (compile
  + dry-run) behind the stepping fleet, and enters at an agreed
  ``start_step`` after a N→N+1 reshard;
* :mod:`~paddle_tpu.resilience.autoscale` — the SLO-driven control
  loop (:class:`~paddle_tpu.resilience.autoscale.SLOPolicy` /
  :class:`~paddle_tpu.resilience.autoscale.Autoscaler`) deciding
  grow/shrink/replan/no-op from monitor-collected signals and
  journaling every decision with its evidence;
* :mod:`~paddle_tpu.resilience.reshard` — checkpoint topology
  remapping: re-slice row-sharded optimizer/embedding state from an
  old world size to a new one, bit-exactly.

Chaos harness: ``python -m paddle_tpu.tools.chaos`` runs a short training
loop under a fault spec and exits nonzero unless the run *recovers* —
final params must match the fault-free trajectory.
"""

from . import faults
from . import retry
from . import guard
from . import watchdog
from . import checkpoint
from .faults import (FaultInjected, TransientFault, FaultInjector,
                     get_injector, set_fault_spec, reset_injector,
                     set_step)
from .retry import (RetryPolicy, RetryExhaustedError, retry_call,
                    with_retries, run_with_timeout)
from .guard import NonFiniteStepWarning, GuardStats, guard_enabled
from .watchdog import (WorkerLostError, HeartbeatWriter, HeartbeatMonitor,
                       wait_cluster)
from .checkpoint import (CheckpointInfo, CorruptCheckpointError,
                         TopologyMismatchError, save_checkpoint,
                         try_load_latest_checkpoint, list_checkpoints,
                         verify_checkpoint, read_topology)
from . import elastic
from . import reshard
from . import autoscale
from .elastic import (ELASTIC_EVICTED_EXIT_CODE, ElasticError,
                      ElasticEvictedError, ElasticTrainer, Membership,
                      agree_membership, reduce_gradients,
                      request_join, pending_joins, gc_epoch_files)
from .reshard import reshard_checkpoint, shard_bounds
from .autoscale import Autoscaler, Decision, SLOPolicy

__all__ = [
    "faults",
    "retry",
    "guard",
    "watchdog",
    "checkpoint",
    "elastic",
    "reshard",
    "autoscale",
    "FaultInjected",
    "TransientFault",
    "FaultInjector",
    "get_injector",
    "set_fault_spec",
    "reset_injector",
    "set_step",
    "RetryPolicy",
    "RetryExhaustedError",
    "retry_call",
    "with_retries",
    "run_with_timeout",
    "NonFiniteStepWarning",
    "GuardStats",
    "guard_enabled",
    "WorkerLostError",
    "HeartbeatWriter",
    "HeartbeatMonitor",
    "wait_cluster",
    "CheckpointInfo",
    "CorruptCheckpointError",
    "TopologyMismatchError",
    "save_checkpoint",
    "try_load_latest_checkpoint",
    "list_checkpoints",
    "verify_checkpoint",
    "read_topology",
    "ELASTIC_EVICTED_EXIT_CODE",
    "ElasticError",
    "ElasticEvictedError",
    "ElasticTrainer",
    "Membership",
    "agree_membership",
    "reduce_gradients",
    "request_join",
    "pending_joins",
    "gc_epoch_files",
    "reshard_checkpoint",
    "shard_bounds",
    "Autoscaler",
    "Decision",
    "SLOPolicy",
]
