"""Retry/timeout with jittered exponential backoff.

The transient-failure policy for every host-side edge the runtime
crosses: checkpoint I/O, executor compilation, fleet bootstrap/barriers.
Deterministic by construction — the jitter RNG is seeded — so a chaos
replay sleeps the same schedule it slept the first time.

Env knobs (defaults in parentheses):

* ``PADDLE_TPU_RETRY_MAX_ATTEMPTS`` (3) — total attempts incl. the first
* ``PADDLE_TPU_RETRY_BASE_DELAY_MS`` (50) — first backoff delay
* ``PADDLE_TPU_RETRY_MAX_DELAY_MS`` (2000) — backoff ceiling
* ``PADDLE_TPU_RETRY_JITTER`` (0.25) — +/- fraction of each delay
* ``PADDLE_TPU_RETRY_SEED`` (0) — jitter RNG seed
"""

import os
import random
import threading
import time
import warnings

from .faults import TransientFault

__all__ = ["RetryPolicy", "RetryExhaustedError", "retry_call",
           "with_retries", "run_with_timeout"]

#: exception types retried by default — injected transients plus the
#: OS-level failures checkpoint I/O actually produces.  Deliberately NOT
#: Exception: a genuine bug (TypeError, ValueError, a jax trace error)
#: must fail fast, not be retried into a 3x-slower identical failure.
DEFAULT_RETRY_ON = (TransientFault, OSError, ConnectionError)


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class RetryExhaustedError(RuntimeError):
    """All attempts failed; ``.last_error`` is the final exception."""

    def __init__(self, message, last_error=None, attempts=0):
        super().__init__(message)
        self.last_error = last_error
        self.attempts = attempts


class RetryPolicy:
    """max_attempts / base_delay / max_delay / multiplier / jitter /
    retry_on, env-defaulted.  ``delays()`` yields the (deterministic)
    backoff schedule between attempts."""

    def __init__(self, max_attempts=None, base_delay=None, max_delay=None,
                 multiplier=2.0, jitter=None, seed=None, retry_on=None):
        self.max_attempts = int(
            max_attempts if max_attempts is not None
            else _env_float("PADDLE_TPU_RETRY_MAX_ATTEMPTS", 3))
        self.base_delay = (
            base_delay if base_delay is not None
            else _env_float("PADDLE_TPU_RETRY_BASE_DELAY_MS", 50) / 1000.0)
        self.max_delay = (
            max_delay if max_delay is not None
            else _env_float("PADDLE_TPU_RETRY_MAX_DELAY_MS", 2000) / 1000.0)
        self.multiplier = float(multiplier)
        self.jitter = (jitter if jitter is not None
                       else _env_float("PADDLE_TPU_RETRY_JITTER", 0.25))
        self.seed = int(seed if seed is not None
                        else _env_float("PADDLE_TPU_RETRY_SEED", 0))
        self.retry_on = tuple(retry_on or DEFAULT_RETRY_ON)

    def delays(self):
        """Backoff delay before attempt i+2, for i in range(attempts-1)."""
        rng = random.Random(self.seed)
        d = self.base_delay
        for _ in range(max(self.max_attempts - 1, 0)):
            j = 1.0 + self.jitter * rng.uniform(-1.0, 1.0)
            yield max(min(d, self.max_delay) * j, 0.0)
            d *= self.multiplier


def retry_call(fn, *args, policy=None, site="", on_retry=None, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying ``policy.retry_on``
    failures with backoff.  Non-retryable exceptions propagate
    immediately; exhausting attempts raises :class:`RetryExhaustedError`
    chaining the last failure.  ``on_retry(attempt, exc, delay)`` is
    notified before each sleep."""
    policy = policy or RetryPolicy()
    delays = policy.delays()
    last = None
    for attempt in range(1, max(policy.max_attempts, 1) + 1):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            last = e
            try:
                delay = next(delays)
            except StopIteration:
                break
            warnings.warn(
                "transient failure%s (attempt %d/%d): %s — retrying in "
                "%.0f ms" % ((" at %s" % site) if site else "", attempt,
                             policy.max_attempts, e, delay * 1000.0),
                RuntimeWarning, stacklevel=2)
            from ..observability import runtime as _obs

            _obs.record_retry(site)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            time.sleep(delay)
    raise RetryExhaustedError(
        "%s failed after %d attempts: %s"
        % (site or getattr(fn, "__name__", "call"),
           policy.max_attempts, last),
        last_error=last, attempts=policy.max_attempts) from last


def with_retries(**policy_kwargs):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args,
                              policy=RetryPolicy(**policy_kwargs),
                              site=getattr(fn, "__name__", ""), **kwargs)

        return wrapped

    return deco


def run_with_timeout(fn, timeout, what="operation", error_cls=None):
    """Run ``fn()`` with a wall-clock deadline.  On timeout raises
    ``error_cls`` (default :class:`TimeoutError`) — the worker thread is
    abandoned (daemonized), which is the only portable option for a call
    stuck inside a native collective; callers are expected to treat the
    raise as fatal for this process's step."""
    if timeout is None or timeout <= 0:
        return fn()
    result = {}

    def _target():
        try:
            result["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — reported to caller
            result["error"] = e

    t = threading.Thread(target=_target, daemon=True,
                         name="paddle_tpu-timeout-%s" % what)
    t.start()
    t.join(timeout)
    if t.is_alive():
        cls = error_cls or TimeoutError
        raise cls("%s did not complete within %.1fs" % (what, timeout))
    if "error" in result:
        raise result["error"]
    return result.get("value")
