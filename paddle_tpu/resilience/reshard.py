"""Checkpoint resharding: remap a saved version from one cluster
topology to another without losing a byte of state.

A checkpoint saved at world size N lays row-sharded tables (ZeRO-1
optimizer moments, distributed embedding tables) out as N contiguous
row-range files under ``<var>.shards/``.  Restoring that version at a
different world size would either fail the topology check
(:class:`~paddle_tpu.resilience.checkpoint.TopologyMismatchError`) or,
on a multi-host layout, silently read misshapen slices.  This module
rewrites the version *in place* for a new world size:

* plain (replicated) ``.npy`` vars and ``state.json`` are copied
  verbatim — replication is topology-independent;
* each ``.shards`` dir is assembled to the full global array (via the
  same overlap reader the loader uses, so arbitrary old layouts work),
  re-sliced into the new world's contiguous row ranges, and written
  back with a fresh ``meta.json``;
* a new ``MANIFEST.json`` records the new topology plus re-checksummed
  files, and the old version dir is replaced with the save-aside idiom
  from :mod:`~paddle_tpu.resilience.checkpoint` — the old data is never
  destroyed before the new version is fully in place.

The transformation is gather-then-scatter by construction, so the
round-trip tests can hold it to a bit-exact standard.
"""

import json
import os
import shutil
import time

import numpy as np

from . import checkpoint as _ckpt
from . import retry as _retry
from ..observability import tracing as _tr
from .atomic import atomic_write

__all__ = ["shard_bounds", "reshard_checkpoint"]

_META_NAME = "meta.json"
_SHARDS_SUFFIX = ".shards"


def shard_bounds(nrows, world):
    """Contiguous ``[(start, stop)]`` row ranges splitting ``nrows`` over
    ``world`` members — equal chunks when divisible, otherwise the first
    ``nrows % world`` members take one extra row (``np.array_split``
    order, matching the executor's optimizer-state partitioner)."""
    nrows, world = int(nrows), int(world)
    if world < 1:
        raise ValueError("world must be >= 1, got %d" % world)
    sizes = [len(c) for c in np.array_split(np.arange(nrows), world)]
    bounds, start = [], 0
    for size in sizes:
        bounds.append((start, start + size))
        start += size
    return bounds


def _reshard_shard_dir(src, dst, new_world, report):
    """Reassemble one var's shard dir and re-slice its rows for
    ``new_world``.  Raises (via the overlap reader) on gaps or missing
    files — resharding must never paper over a torn source."""
    from .. import io as _io

    meta_path = os.path.join(src, _META_NAME)
    with open(meta_path) as f:
        meta = json.load(f)
    shape = tuple(meta["shape"])
    name = os.path.basename(src)[:-len(_SHARDS_SUFFIX)]
    entries = _io._shard_entries(src, meta)
    full = _io._read_sharded_region(
        entries, meta, tuple((0, d) for d in shape), name)
    os.makedirs(dst)
    rest = tuple((0, d) for d in shape[1:])
    new_files = []
    for start, stop in shard_bounds(shape[0] if shape else 0, new_world):
        if start == stop:
            # more members than rows: the extra members simply hold no
            # slice of this var (the loader assembles from whoever does)
            continue
        bounds = ((start, stop),) + rest
        fname = _io._shard_fname(bounds)
        new_files.append(fname)
        _io._atomic_np_save(os.path.join(dst, fname), full[start:stop])
    atomic_write(
        os.path.join(dst, _META_NAME),
        lambda f: json.dump({"shape": list(shape),
                             "dtype": str(meta["dtype"]),
                             "files": sorted(new_files)}, f),
        text=True)
    report.append({"var": name, "shape": list(shape),
                   "old_files": len(entries), "new_files": len(new_files)})


def _reshard_tree(src, dst, new_world, report):
    os.makedirs(dst, exist_ok=True)
    for name in sorted(os.listdir(src)):
        s, d = os.path.join(src, name), os.path.join(dst, name)
        if os.path.isdir(s):
            if name.endswith(_SHARDS_SUFFIX) \
                    and os.path.exists(os.path.join(s, _META_NAME)):
                _reshard_shard_dir(s, d, new_world, report)
            else:
                _reshard_tree(s, d, new_world, report)
        else:
            shutil.copy2(s, d)


def reshard_checkpoint(path, new_topology, policy=None):
    """Rewrite version dir ``path`` in place for ``new_topology`` (a
    manifest-style dict; ``new_topology["world"]`` drives the row
    re-slicing).  Returns a report list — one entry per resharded var —
    and journals an urgent ``reshard`` event.  The source is verified
    first and replaced atomically; a failure at any point leaves the
    original version untouched."""
    path = os.path.normpath(path)
    root = os.path.dirname(path)
    manifest = _ckpt.verify_checkpoint(path)
    step = int(manifest.get("step", _ckpt._parse_step(path) or 0))
    old_topo = manifest.get("topology")
    new_topo = dict(new_topology or {})
    new_world = int(new_topo.get("world", 1))
    if new_world < 1:
        raise ValueError(
            "new topology needs world >= 1, got %r" % (new_topo,))
    t0 = time.perf_counter()

    def _attempt():
        tmp = os.path.join(root, ".tmp-%08d-%d" % (step, os.getpid()))
        shutil.rmtree(tmp, ignore_errors=True)
        report = []
        try:
            os.makedirs(tmp)
            for name in sorted(os.listdir(path)):
                if name == _ckpt.MANIFEST_NAME:
                    continue  # regenerated below with fresh checksums
                s, d = os.path.join(path, name), os.path.join(tmp, name)
                if os.path.isdir(s):
                    if name.endswith(_SHARDS_SUFFIX) \
                            and os.path.exists(os.path.join(s, _META_NAME)):
                        _reshard_shard_dir(s, d, new_world, report)
                    else:
                        _reshard_tree(s, d, new_world, report)
                else:
                    shutil.copy2(s, d)
            files = {}
            for rel, full in _ckpt._walk_files(tmp):
                files[rel] = {"sha256": _ckpt._file_sha256(full),
                              "size": os.path.getsize(full)}
            new_manifest = dict(manifest)
            new_manifest["files"] = files
            new_manifest["topology"] = new_topo
            new_manifest["wall_time"] = time.time()
            if old_topo:
                new_manifest["resharded_from"] = dict(old_topo)
            tp = _tr.current_traceparent()
            if tp:
                # followers awaiting this manifest can join the
                # leader's recovery trace from the file itself
                new_manifest["traceparent"] = tp
            atomic_write(
                os.path.join(tmp, _ckpt.MANIFEST_NAME),
                lambda f: json.dump(new_manifest, f, indent=1), text=True)
            aside = os.path.join(
                root, ".old-%08d-%d" % (step, os.getpid()))
            shutil.rmtree(aside, ignore_errors=True)
            os.rename(path, aside)
            os.rename(tmp, path)
            shutil.rmtree(aside, ignore_errors=True)
            return report
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    with _tr.span("elastic.reshard", step=step,
                  old_world=(old_topo or {}).get("world"),
                  new_world=new_world):
        report = _retry.retry_call(
            _attempt, policy=policy,
            site="reshard_checkpoint(step=%d)" % step)
    from ..observability import runtime as _obs

    _obs.record_reshard(
        step, (old_topo or {}).get("world"), new_world, len(report),
        (time.perf_counter() - t0) * 1000.0, path)
    return report
