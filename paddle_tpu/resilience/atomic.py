"""One atomic-write idiom for the whole runtime (stdlib-only, so io.py,
faults, checkpoint and watchdog can all share it without import cycles):
write-to-tmp, optional fsync, ``os.replace`` — a crash mid-write can
never leave a torn file under the final name, and the previous file (if
any) survives intact."""

import os

__all__ = ["atomic_write"]


def atomic_write(path, writer, fsync=True, text=False):
    """``writer(fileobj)`` produces the content; ``path`` must already
    carry its extension (handing numpy an open file object stops it from
    appending one)."""
    tmp = "%s.tmp-%d" % (path, os.getpid())
    try:
        with open(tmp, "w" if text else "wb") as f:
            writer(f)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
