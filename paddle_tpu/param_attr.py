"""ParamAttr (reference: ``python/paddle/fluid/param_attr.py``)."""

__all__ = ["ParamAttr", "WeightNormParamAttr"]


class ParamAttr:
    """Parameter attributes (reference param_attr.py).

    TPU-native extension: ``shard_spec`` annotates the parameter with a
    PartitionSpec-like tuple of mesh axis names for tensor parallelism —
    e.g. ``shard_spec=[None, "model"]`` column-shards an [in, out] weight
    over the model axis (Megatron column-parallel), ``["model", None]``
    row-shards it.  Honored when the program runs under
    ``CompiledProgram.with_data_parallel`` with
    ``BuildStrategy.tensor_parallel_degree > 1`` (SURVEY §2.3 TP row:
    TP is free via GSPMD once params carry PartitionSpecs)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 do_model_average=False, shard_spec=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        self.shard_spec = tuple(shard_spec) if shard_spec is not None else None

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else False
        from .initializer import Initializer

        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        raise TypeError("Unsupported ParamAttr spec: %r" % (arg,))

    def _set_default_initializer(self, initializer):
        if self.initializer is None:
            self.initializer = initializer

    def _to_kwargs(self, with_initializer=False):
        kwargs = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "gradient_clip_attr": self.gradient_clip,
            "do_model_average": self.do_model_average,
            "shard_spec": self.shard_spec,
        }
        if with_initializer:
            kwargs["initializer"] = self.initializer
        return kwargs


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
