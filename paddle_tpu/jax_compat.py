"""Version-compatibility shims for the pinned jax.

The container pins jax 0.4.x while parts of this codebase were written
against newer jax: ``jax.shard_map`` only became a top-level export
(with ``check_rep`` renamed ``check_vma``) after 0.4, and
``Lowered.as_text(debug_info=True)`` grew the kwarg later too.  Every
post-0.4 API goes through here so a version gap degrades gracefully
instead of killing ~150 tier-1 tests at import time (the PR-2 lesson —
see ``ops/registry.py``'s ``jax.typeof`` guard).
"""

__all__ = ["shard_map", "lowered_as_text", "axis_size"]

try:  # jax >= 0.6: top-level export, check_vma spelling
    from jax import shard_map as _shard_map

    _NATIVE_VMA = True
except ImportError:  # jax 0.4.x: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    _NATIVE_VMA = False


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=None, **kw):
    """``jax.shard_map`` across jax versions: resolves the export
    location and translates ``check_vma`` to the old ``check_rep``
    spelling when running on 0.4.x."""
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    if check_vma is not None:
        kwargs["check_vma" if _NATIVE_VMA else "check_rep"] = check_vma
    return _shard_map(f, **kwargs)


def axis_size(axis_name):
    """``jax.lax.axis_size`` (post-0.4) or its 0.4.x equivalent — a
    ``psum(1)`` over the axis, which XLA constant-folds to the same
    static mesh-axis size without emitting a collective."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def lowered_as_text(lowered, debug_info=False):
    """``jax.stages.Lowered.as_text`` with the ``debug_info`` kwarg
    when this jax supports it.  On 0.4.x (no such kwarg, and the plain
    text drops location metadata) a debug request renders the MLIR
    module with ``enable_debug_info`` instead, which carries the same
    ``named_scope`` attribution the profiler tooling greps for."""
    try:
        return lowered.as_text(debug_info=debug_info)
    except TypeError:
        if debug_info:
            try:
                return lowered.compiler_ir().operation.get_asm(
                    enable_debug_info=True)
            except Exception:  # pragma: no cover - fall through
                pass
        return lowered.as_text()
