"""DistributeTranspiler (reference:
``python/paddle/fluid/transpiler/distribute_transpiler.py:377``).

The reference rewrites programs three ways:
- **pserver mode**: slice params into blocks, replace grads with
  send/recv ops, emit a pserver program run by listen_and_serv
  (``:836``) — per-step RPC.
- **nccl2 mode** (``:261``): append a gen_nccl_id bootstrap op; the program
  itself stays local and BuildStrategy carries num_trainers/trainer_id.
- **collective mode** (``:313``): insert explicit c_allreduce ops.

TPU-native: data-parallel gradient exchange is GSPMD's job — one program
jitted over a mesh, collectives over ICI/DCN inserted by the partitioner,
membership from the jax coordination service.  So:
- nccl2/collective modes record the trainer topology (consumed by
  CompiledProgram/fleet for mesh construction) and, for collective mode,
  insert the same program-level `c_allreduce_sum` ops the reference does
  (identity under GSPMD, psum under shard_map execution).
- pserver mode (the reference default) keeps its user-facing semantics
  but not its mechanism: sparse lookup tables are marked row-sharded over
  the mesh (the distributed-lookup-table role), dense training is
  GSPMD's job, sync_mode=False becomes AsyncSGD staleness-1 delayed
  gradient exchange, and there is no separate pserver program — per-step
  RPC against host servers defeats ICI, so get_pserver_program raises
  with that guidance (the >HBM case is host_table.py).
"""

from ..framework import default_main_program, default_startup_program

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "slice_variable", "mark_sparse_tables"]


class DistributeTranspilerConfig:
    """reference distribute_transpiler.py:131"""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    sync_mode = True
    runtime_split_send_recv = False
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100
    # reference default (distribute_transpiler.py:162); sync_mode and
    # enable_dc_asgd apply to THIS mode only — nccl2/collective are
    # inherently synchronous (reference precedence)
    mode = "pserver"
    print_log = False
    wait_port = True
    collective_mode = None
    # auto=True (or mode="auto"): route transpile() through the
    # auto-parallelism planner (parallel.auto_transpile) instead of a
    # hand-picked mode — the planner searches DP/pipeline/... against
    # the PADDLE_TPU_CLUSTER_SPEC cost model, applies a DP-family
    # winner in place, and stashes the PlanResult on program._auto_plan
    auto = False


def mark_sparse_tables(program):
    """Mark every sparse/distributed ``lookup_table`` parameter
    ``_is_distributed`` so it row-shards over the mesh data axis (the
    TPU replacement for the pserver-sliced distributed lookup table,
    ``transpiler/distribute_transpiler.py:353-376``).  Params live in
    the global block even when the lookup runs in a sub-block, hence
    the recursive var lookup."""
    for block in program.blocks:
        for op in block.ops:
            if op.type not in ("lookup_table", "lookup_table_v2"):
                continue
            if not op.attr("is_sparse") and not op.attr("is_distributed"):
                continue
            w = block.var_recursive(op.input("W")[0])
            w._is_distributed = True
            op._set_attr("is_distributed", True)


def slice_variable(var_list, slice_count, min_block_size=8192):
    """Param slicing plan (reference distribute_transpiler.py:85) — kept for
    API/test parity and used by the sharded-embedding planner."""
    blocks = []
    for var in var_list:
        split_count = slice_count
        import numpy as np

        var_numel = int(np.prod(var.shape))
        max_pserver_count = int(var_numel / min_block_size)
        if max_pserver_count == 0:
            max_pserver_count = 1
        if max_pserver_count < slice_count:
            split_count = max_pserver_count
        block_size = int((var_numel + split_count - 1) / split_count)
        if len(var.shape) >= 2:
            dim1 = int(np.prod(var.shape[1:]))
            remains = block_size % dim1
            if remains != 0:
                block_size += dim1 - remains
        split_count = int((var_numel + block_size - 1) / block_size)
        for block_id in range(split_count):
            curr_block_size = min(block_size,
                                  var_numel - (block_id * block_size))
            blocks.append("%s:%d:%d" % (var.name, block_id, curr_block_size))
    return blocks


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self.trainer_id = 0
        self.trainers = 1
        self.endpoints = []

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        program = program or default_main_program()
        startup_program = startup_program or default_startup_program()
        self.trainer_id = trainer_id
        mode = getattr(self.config, "mode", "pserver")
        if isinstance(trainers, str):
            self.endpoints = trainers.split(",")
            self.trainers = len(self.endpoints)
        else:
            self.trainers = int(trainers)
        if mode == "auto" or getattr(self.config, "auto", False):
            # planner-routed transpile: search, prove, apply
            from ..parallel.planner import (apply_plan, auto_transpile,
                                            resolve_cluster_spec)

            program._trainer_id = trainer_id
            program._num_trainers = self.trainers
            if self.trainers <= 1:
                return
            result = auto_transpile(
                program, resolve_cluster_spec(chips=self.trainers),
                startup_program=startup_program)
            apply_plan(program, result,
                       startup_program=startup_program,
                       rank=trainer_id)
            return
        if getattr(self.config, "geo_sgd_mode", False):
            # reference geo-SGD (distribute_transpiler.py:131 geo fields):
            # local steps + periodic delta sync, redesigned as a gated
            # delta-allreduce (collective.GeoSGD)
            from .collective import GeoSGD

            program._trainer_id = trainer_id
            program._num_trainers = self.trainers
            GeoSGD(need_push_nums=getattr(
                self.config, "geo_sgd_need_push_nums", 100)).transpile(
                program=program, startup_program=startup_program,
                rank=trainer_id, nranks=self.trainers,
            )
            return
        if mode == "pserver":
            # The TPU redesign of PS mode: sparse lookup tables become
            # row-sharded over the mesh (the distributed-lookup-table
            # role), dense "shards" are GSPMD's job — no program split,
            # no pserver program.  sync_mode=False (the reference async
            # Communicator, communicator.h:160 barrier-free send/recv
            # threads) becomes staleness-1 delayed gradient exchange;
            # enable_dc_asgd adds delay compensation.  Reference
            # precedence kept: these knobs apply to pserver mode ONLY.
            program._trainer_id = trainer_id
            program._num_trainers = self.trainers
            mark_sparse_tables(program)
            if not sync_mode or not getattr(self.config, "sync_mode",
                                            True):
                from .collective import AsyncSGD

                AsyncSGD(dc_asgd=getattr(
                    self.config, "enable_dc_asgd", False)).transpile(
                    program=program, startup_program=startup_program,
                    rank=trainer_id, nranks=self.trainers,
                )
            return
        if mode in ("nccl2", "grad_allreduce", "collective", "local_sgd"):
            # topology recorded on the program; mesh construction and
            # collective insertion happen at jit time (GSPMD) — the
            # gen_nccl_id bootstrap is subsumed by jax.distributed
            program._trainer_id = trainer_id
            program._num_trainers = self.trainers
            if mode == "local_sgd":
                # reference _transpile_collective(collective_mode=
                # 'local_sgd') → collective.py LocalSGD: snapshot params,
                # train locally, allreduce the deltas each step
                from .collective import LocalSGD

                LocalSGD().transpile(
                    program=program, startup_program=startup_program,
                    rank=trainer_id, nranks=self.trainers,
                )
            elif mode in ("grad_allreduce", "collective"):
                from .collective import GradAllReduce

                GradAllReduce().transpile(
                    program=program, startup_program=startup_program,
                    rank=trainer_id, nranks=self.trainers,
                )
            return
        raise ValueError(
            "unknown transpiler mode %r: supported are pserver, nccl2, "
            "grad_allreduce, collective, local_sgd" % (mode,)
        )

    def get_trainer_program(self, wait_port=True):
        return default_main_program()

    def get_pserver_program(self, endpoint):
        raise NotImplementedError(
            "no pserver program on TPU — see DistributeTranspiler.transpile"
        )

    def get_pserver_programs(self, endpoint):
        raise NotImplementedError(
            "no pserver program on TPU — see DistributeTranspiler.transpile"
        )

    def get_startup_program(self, endpoint, pserver_program=None,
                            startup_program=None):
        raise NotImplementedError(
            "no pserver startup program on TPU"
        )
