"""Distributed transpilers (reference:
``python/paddle/fluid/transpiler/``)."""

from .distribute_transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from .ps_dispatcher import HashName, RoundRobin
from . import collective

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "HashName",
    "RoundRobin",
    "memory_optimize",
    "release_memory",
    "collective",
]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """Legacy var-reuse pass (reference
    memory_optimization_transpiler.py).  XLA's buffer assignment + the
    executor's donated params already subsume in-place reuse under jit, so
    this is a recorded no-op for API parity."""
    return None


def release_memory(input_program, skip_opt_set=None):
    return None
