"""Collective transpilers (reference:
``python/paddle/fluid/transpiler/collective.py``: GradAllReduce:175 inserts
c_allreduce_sum after each grad + scales the loss grad; LocalSGD:263
snapshots params and allreduces deltas).

On TPU the inserted ops are identity under GSPMD (which already reduces
grads globally because the batch is sharded) and real psums under shard_map
execution — so a transpiled program is correct either way."""

from ..framework import default_main_program, default_startup_program

__all__ = ["GradAllReduce", "LocalSGD", "GeoSGD", "AsyncSGD", "Collective",
           "ensure_comm_ring"]

OP_ROLE_BACKWARD = "backward"


def ensure_comm_ring(startup_program, ring_id, rank=0, nranks=1):
    """Append the ``c_gen_nccl_id`` → ``c_comm_init`` bootstrap pair for
    ``ring_id`` to a startup program, idempotently (the reference emits
    this pair per ring in C++; on TPU the ops are structural no-ops —
    mesh membership comes from the jax coordination service — but the
    static analyzer's ``collective-ring`` check pairs them per ring, and
    every emitter of ring-stamped collectives calls this so the ring is
    declared exactly once)."""
    block = startup_program.global_block()
    for op in block.ops:
        if op.type == "c_gen_nccl_id" \
                and op.attrs.get("ring_id") == ring_id:
            return
    nccl_id = block.create_var(name="tpu_comm_id_%s" % ring_id,
                               shape=[1], dtype="int32", persistable=True)
    block.append_op(
        type="c_gen_nccl_id", outputs={"Out": [nccl_id]},
        attrs={"rank": rank, "ring_id": ring_id},
    )
    block.append_op(
        type="c_comm_init", inputs={"X": [nccl_id]},
        attrs={"nranks": nranks, "rank": rank, "ring_id": ring_id},
    )


class Collective:
    def __init__(self, nrings=1):
        self.nrings = nrings
        self.rank = 0
        self.nranks = 1

    def transpile(self, startup_program=None, program=None, rank=0,
                  nranks=1, endpoints=None, current_endpoint=None,
                  wait_port=True):
        self.rank = rank
        self.nranks = nranks
        self.main_program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self._transpile_startup_program()
        self._transpile_main_program()

    def _transpile_startup_program(self):
        # reference appends c_gen_nccl_id + c_comm_init PER RING; the
        # old code bootstrapped ring 0 only, so Collective(nrings=2)
        # emitted collectives on a ring the startup never declared (the
        # pairing gap the collective-ring check now reports)
        for ring in range(self.nrings):
            ensure_comm_ring(self.startup_program, ring,
                             rank=self.rank, nranks=self.nranks)

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    def _transpile_main_program(self):
        if self.nranks <= 1:
            return
        block = self.main_program.global_block()
        # find PARAMETER grads by op role; insert allreduce right after
        # the producing op, scaled 1/nranks (reference collective.py:205
        # iterates param_grads).  Activation grads must NOT be exchanged:
        # they legitimately differ per worker (each holds its own batch
        # shard), averaging them mid-backward corrupts every downstream
        # grad under shard_map — and even under GSPMD (identity) each
        # extra collective inflates the static ICI schedule ~6x on an
        # MLP, which is exactly what the analyzer's cost model showed.
        #
        # The grad THE OPTIMIZER CONSUMES is authoritative: for a shared
        # parameter backward emits partials (w@GRAD, w@GRAD@RENAME_0)
        # and a fan-in sum producing w@GRAD@SUM_0 — allreducing the
        # partial while the optimizer reads the sum would apply
        # avg(partial1)+local(partial2), silently divergent per worker.
        param_grads = {
            p.name + "@GRAD" for p in self.main_program.all_parameters()
        }
        for op in block.ops:
            if op.attrs.get("op_role") == "optimize" and op.input("Grad"):
                g = op.input("Grad")[0]
                p = op.input("Param")
                if p:
                    param_grads.discard(p[0] + "@GRAD")
                param_grads.add(g)
        new_ops = []
        from ..framework import Operator

        for op in block.ops:
            new_ops.append(op)
            if op.attrs.get("op_role") != OP_ROLE_BACKWARD:
                continue
            grad_outs = [
                n for n in op.output_arg_names if n in param_grads
            ]
            for g in grad_outs:
                v = block._find_var_recursive(g)
                if v is None:
                    continue
                # averaging rides on the collective (pre_scale) so the
                # same program is exact under BOTH shard_map (pmean) and
                # GSPMD (identity — a separate scale op would shrink it)
                new_ops.append(Operator(
                    block, "c_allreduce_sum", {"X": [g]}, {"Out": [g]},
                    {"ring_id": 0, "pre_scale": 1.0 / self.nranks,
                     "op_role": OP_ROLE_BACKWARD},
                ))
        block.ops = new_ops
        self.main_program._bump_version()


class LocalSGD(Collective):
    """Periodic model averaging (reference collective.py:263): snapshot
    params, train locally, allreduce param deltas."""

    def _transpile_main_program(self):
        if self.nranks <= 1:
            return
        block = self.main_program.global_block()
        from ..framework import Operator
        from ..initializer import ConstantInitializer
        from ..layer_helper import LayerHelper

        helper = LayerHelper("local_sgd")
        for p in self.main_program.all_parameters():
            snap_name = p.name + "@SNAPSHOT"
            snap = block.create_var(
                name=snap_name, shape=p.shape, dtype=p.dtype,
                persistable=True,
            )
            sb = self.startup_program.global_block()
            sv = sb.create_var(name=snap_name, shape=p.shape, dtype=p.dtype,
                               persistable=True)
            sb.append_op(
                type="assign", inputs={"X": [p.name]},
                outputs={"Out": [snap_name]},
            )
            # delta = snapshot - param ; allreduce ; param = snapshot - delta/n
            delta = p.name + "@DELTA"
            block.create_var(name=delta, shape=p.shape, dtype=p.dtype)
            block.append_op(
                type="elementwise_sub",
                inputs={"X": [snap_name], "Y": [p.name]},
                outputs={"Out": [delta]},
            )
            block.append_op(
                type="c_allreduce_sum", inputs={"X": [delta]},
                outputs={"Out": [delta]},
                attrs={"ring_id": 0, "pre_scale": 1.0 / self.nranks},
            )
            block.append_op(
                type="elementwise_sub",
                inputs={"X": [snap_name], "Y": [delta]},
                outputs={"Out": [p.name]},
            )
            block.append_op(
                type="assign", inputs={"X": [p.name]},
                outputs={"Out": [snap_name]},
            )
        self.main_program._bump_version()


class GeoSGD(Collective):
    """Geo-SGD (reference ``distribute_transpiler.py:131`` geo fields +
    the async geo ``Communicator`` mode): each worker trains locally and
    only every ``need_push_nums`` steps the parameter *deltas* since the
    last sync are averaged across workers.

    TPU redesign: the reference's pserver delta push/pull becomes a gated
    delta-allreduce appended after the optimizer — a persistable step
    counter drives a 0/1 gate, so off-sync steps are pure-local (the
    selects keep the program one static jit; under GSPMD the allreduce is
    an identity and XLA folds the gate arithmetic)."""

    def __init__(self, need_push_nums=100, nrings=1):
        super().__init__(nrings)
        self.need_push_nums = int(need_push_nums)

    def _transpile_main_program(self):
        if self.nranks <= 1:
            return
        block = self.main_program.global_block()
        sb = self.startup_program.global_block()

        step = "geo_sgd@STEP"
        block.create_var(name=step, shape=[1], dtype="float32",
                         persistable=True)
        sb.create_var(name=step, shape=[1], dtype="float32",
                      persistable=True)
        sb.append_op(
            type="fill_constant", outputs={"Out": [step]},
            attrs={"shape": [1], "dtype": "float32", "value": 0.0},
        )
        block.append_op(
            type="increment", inputs={"X": [step]}, outputs={"Out": [step]},
            attrs={"step": 1.0},
        )
        k = "geo_sgd@K"
        block.create_var(name=k, shape=[1], dtype="float32")
        block.append_op(
            type="fill_constant", outputs={"Out": [k]},
            attrs={"shape": [1], "dtype": "float32",
                   "value": float(self.need_push_nums)},
        )
        modv = "geo_sgd@MOD"
        block.create_var(name=modv, shape=[1], dtype="float32")
        block.append_op(
            type="elementwise_mod", inputs={"X": [step], "Y": [k]},
            outputs={"Out": [modv]},
        )
        zero = "geo_sgd@ZERO"
        block.create_var(name=zero, shape=[1], dtype="float32")
        block.append_op(
            type="fill_constant", outputs={"Out": [zero]},
            attrs={"shape": [1], "dtype": "float32", "value": 0.0},
        )
        gate_b = "geo_sgd@GATE_B"
        block.create_var(name=gate_b, shape=[1], dtype="bool")
        block.append_op(
            type="equal", inputs={"X": [modv], "Y": [zero]},
            outputs={"Out": [gate_b]},
        )
        gate = "geo_sgd@GATE"
        block.create_var(name=gate, shape=[1], dtype="float32")
        block.append_op(
            type="cast", inputs={"X": [gate_b]}, outputs={"Out": [gate]},
            attrs={"in_dtype": "bool", "out_dtype": "float32"},
        )
        # reset the counter on sync (step *= 1-gate): it never exceeds k,
        # so float32 increment can't saturate on billion-step runs
        notg = "geo_sgd@NOTGATE"
        block.create_var(name=notg, shape=[1], dtype="float32")
        block.append_op(
            type="scale", inputs={"X": [gate]}, outputs={"Out": [notg]},
            attrs={"scale": -1.0, "bias": 1.0},
        )
        block.append_op(
            type="elementwise_mul", inputs={"X": [step], "Y": [notg]},
            outputs={"Out": [step]},
        )

        for p in self.main_program.all_parameters():
            snap = p.name + "@GEO_SNAPSHOT"
            block.create_var(name=snap, shape=p.shape, dtype=p.dtype,
                             persistable=True)
            sb.create_var(name=snap, shape=p.shape, dtype=p.dtype,
                          persistable=True)
            sb.append_op(
                type="assign", inputs={"X": [p.name]},
                outputs={"Out": [snap]},
            )

            def tmp(suffix):
                n = p.name + suffix
                block.create_var(name=n, shape=p.shape, dtype=p.dtype)
                return n

            delta = tmp("@GEO_DELTA")
            block.append_op(
                type="elementwise_sub", inputs={"X": [snap], "Y": [p.name]},
                outputs={"Out": [delta]},
            )
            block.append_op(
                type="c_allreduce_sum", inputs={"X": [delta]},
                outputs={"Out": [delta]},
                attrs={"ring_id": 0, "pre_scale": 1.0 / self.nranks},
            )
            synced = tmp("@GEO_SYNCED")
            block.append_op(
                type="elementwise_sub", inputs={"X": [snap], "Y": [delta]},
                outputs={"Out": [synced]},
            )
            # param = param + gate * (synced - param)
            diff = tmp("@GEO_DIFF")
            block.append_op(
                type="elementwise_sub",
                inputs={"X": [synced], "Y": [p.name]},
                outputs={"Out": [diff]},
            )
            block.append_op(
                type="elementwise_mul", inputs={"X": [diff], "Y": [gate]},
                outputs={"Out": [diff]},
            )
            block.append_op(
                type="elementwise_add",
                inputs={"X": [p.name], "Y": [diff]},
                outputs={"Out": [p.name]},
            )
            # snapshot = snapshot + gate * (param - snapshot)
            sdiff = tmp("@GEO_SDIFF")
            block.append_op(
                type="elementwise_sub",
                inputs={"X": [p.name], "Y": [snap]},
                outputs={"Out": [sdiff]},
            )
            block.append_op(
                type="elementwise_mul", inputs={"X": [sdiff], "Y": [gate]},
                outputs={"Out": [sdiff]},
            )
            block.append_op(
                type="elementwise_add", inputs={"X": [snap], "Y": [sdiff]},
                outputs={"Out": [snap]},
            )
        self.main_program._bump_version()


class AsyncSGD(Collective):
    """Async-SGD (the reference's ``sync_mode=False`` parameter-server
    mode: ``communicator.h:160-179`` send/recv threads push gradients and
    pull parameters without barriers, so every update lands with roughly
    one step of staleness relative to the gradients of the other
    trainers).

    TPU redesign — staleness-1 delayed gradient exchange.  A persistable
    buffer per gradient holds the *previous* step's local gradient.  At
    the top of the step the buffers are allreduce-averaged; because this
    collective only carries last step's data, it has no data dependency
    on the current forward/backward and XLA is free to overlap it with
    compute (the latency-hiding the reference bought with communicator
    threads, here bought by the scheduler).  The optimizer consumes the
    stale average while the fresh local gradient replaces the buffer.

    Optional DC-ASGD delay compensation (``DistributeTranspilerConfig.
    enable_dc_asgd``; the reference wires this flag into its async
    pserver optimizer blocks): the applied gradient becomes
    ``g + lambda * g * g * (w - w_snapshot)`` where ``w_snapshot`` is the
    parameter value at the step the buffered gradient was produced —
    a first-order correction of the staleness (Zheng et al., 2017).

    Under GSPMD execution the allreduce is an identity and the sharded
    batch already averages gradients globally, so the program degrades to
    exact delayed-gradient descent — which is what the parity test
    asserts; under shard_map the collective is a real psum.
    """

    def __init__(self, dc_asgd=False, dc_lambda=0.04, nrings=1):
        super().__init__(nrings)
        self.dc_asgd = bool(dc_asgd)
        self.dc_lambda = float(dc_lambda)

    def _transpile_main_program(self):
        from ..framework import Operator

        if self.nranks <= 1:
            # single trainer: nothing to overlap — the reference's
            # one-trainer async run is effectively synchronous, and a
            # delayed-gradient rewrite would only hurt convergence
            return
        block = self.main_program.global_block()
        sb = self.startup_program.global_block()

        grad_of = {p.name + "@GRAD": p
                   for p in self.main_program.all_parameters()}

        # last producer index per param-grad (fan-in dedup guarantees the
        # optimizer reads the final write)
        last_prod = {}
        for i, op in enumerate(block.ops):
            for g in op.output_arg_names:
                if g in grad_of:
                    last_prod[g] = i
        if not last_prod:
            return

        head = []   # ops prepended before the whole block
        after = {}  # producer index -> ops appended right after it
        for g, p in grad_of.items():
            if g not in last_prod:
                continue
            gv = block._find_var_recursive(g)
            gshape = list(gv.shape) if gv is not None else list(p.shape)
            gdtype = gv.dtype if gv is not None else p.dtype

            buf = g + "@ASYNC_BUF"
            stale = g + "@ASYNC_STALE"
            block.create_var(name=buf, shape=gshape, dtype=gdtype,
                             persistable=True)
            block.create_var(name=stale, shape=gshape, dtype=gdtype)
            sb.create_var(name=buf, shape=gshape, dtype=gdtype,
                          persistable=True)
            sb.append_op(
                type="fill_constant", outputs={"Out": [buf]},
                attrs={"shape": gshape, "dtype": gdtype, "value": 0.0},
            )

            # the head collective ships LAST step's gradients: no data
            # dependency on this step's compute, so it can overlap
            head.append(Operator(
                block, "c_allreduce_sum", {"X": [buf]}, {"Out": [stale]},
                {"ring_id": 0, "pre_scale": 1.0 / max(self.nranks, 1),
                 "op_role": OP_ROLE_BACKWARD},
            ))
            if self.dc_asgd:
                snap = p.name + "@ASYNC_PSNAP"
                block.create_var(name=snap, shape=list(p.shape),
                                 dtype=p.dtype, persistable=True)
                sb.create_var(name=snap, shape=list(p.shape),
                              dtype=p.dtype, persistable=True)
                sb.append_op(type="assign", inputs={"X": [p.name]},
                             outputs={"Out": [snap]})
                diff = g + "@ASYNC_DIFF"
                sq = g + "@ASYNC_SQ"
                block.create_var(name=diff, shape=gshape, dtype=gdtype)
                block.create_var(name=sq, shape=gshape, dtype=gdtype)
                head.append(Operator(
                    block, "elementwise_sub",
                    {"X": [p.name], "Y": [snap]}, {"Out": [diff]}, {}))
                head.append(Operator(
                    block, "elementwise_mul",
                    {"X": [stale], "Y": [stale]}, {"Out": [sq]}, {}))
                head.append(Operator(
                    block, "elementwise_mul",
                    {"X": [sq], "Y": [diff]}, {"Out": [sq]}, {}))
                head.append(Operator(
                    block, "scale", {"X": [sq]}, {"Out": [sq]},
                    {"scale": self.dc_lambda}))
                head.append(Operator(
                    block, "elementwise_add",
                    {"X": [stale], "Y": [sq]}, {"Out": [stale]}, {}))
                # snapshot w for the gradient being produced THIS step
                head.append(Operator(
                    block, "assign", {"X": [p.name]}, {"Out": [snap]}, {}))

            after.setdefault(last_prod[g], []).extend([
                Operator(block, "assign", {"X": [g]}, {"Out": [buf]},
                         {"op_role": OP_ROLE_BACKWARD}),
                Operator(block, "assign", {"X": [stale]}, {"Out": [g]},
                         {"op_role": OP_ROLE_BACKWARD}),
            ])

        new_ops = list(head)
        for i, op in enumerate(block.ops):
            new_ops.append(op)
            new_ops.extend(after.get(i, ()))
        block.ops = new_ops
        self.main_program._bump_version()


ASYNC_TOY_W0 = (1.0, -2.0, 3.0, 0.5)


def build_toy_async_program(dc_asgd=False, nranks=2, lr=0.1):
    """The 4-weight SGD toy used by every AsyncSGD oracle (tests +
    dryrun): loss = mean((w - x)^2), so d/dw = (w - x)/2.  Returns
    ``(main, startup, loss, w0)`` with the async transpile applied."""
    import numpy as np

    import paddle_tpu as fluid

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    w0 = np.array(ASYNC_TOY_W0, "float32")
    with fluid.program_guard(main, startup):
        w = fluid.layers.create_parameter(
            [4], "float32", name="w",
            default_initializer=fluid.initializer.NumpyArrayInitializer(w0))
        x = fluid.layers.data(name="x", shape=[4], append_batch_size=False)
        d = fluid.layers.elementwise_sub(w, x)
        loss = fluid.layers.reduce_mean(fluid.layers.elementwise_mul(d, d))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    AsyncSGD(dc_asgd=dc_asgd).transpile(
        program=main, startup_program=startup, rank=0, nranks=nranks)
    return main, startup, loss, w0


def async_two_worker_probe(devices, lr=0.1):
    """Shared recipe for the AsyncSGD cross-worker oracle (used by
    tests/test_async_sgd.py and __graft_entry__._dryrun_async_sgd): build
    a tiny async-transpiled program, run one step on a 2-worker shard_map
    mesh with diverged gradient buffers, and return
    ``(w0, x_w, buf_w, w_out, buf_out)`` for the caller to assert
    - both workers applied the MEAN of the buffered (previous-step) grads
    - each buffer took its own fresh local gradient (w - x)/2.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pre-0.8 fallback
        from jax.experimental.shard_map import shard_map

    from ..executor import _run_ops_into_env
    from ..ops import registry as op_registry

    main, startup, _loss, w0 = build_toy_async_program(lr=lr)
    block = main.global_block()
    lr_names = [n for n in block.vars if "learning_rate" in n]

    mesh = Mesh(np.array(devices[:2]), ("workers",))
    x_w = np.stack([np.arange(4, dtype="float32"),
                    np.arange(4, dtype="float32") + 10.0])
    buf_w = np.stack([np.full(4, 2.0, "float32"),
                      np.full(4, 4.0, "float32")])

    def per_worker(w, buf, x):
        ctx = op_registry.LoweringContext(mode="train")
        ctx.collective_axis = "workers"
        env = {"w": w[0], "w@GRAD@ASYNC_BUF": buf[0], "x": x[0]}
        for n in lr_names:  # startup-filled persistable
            env[n] = jnp.asarray([lr], jnp.float32)
        _run_ops_into_env(block, env, ctx)
        return env["w"][None], env["w@GRAD@ASYNC_BUF"][None]

    f = shard_map(per_worker, mesh=mesh, in_specs=(P("workers"),) * 3,
                  out_specs=(P("workers"),) * 2)
    w_out, buf_out = [np.asarray(v) for v in f(
        jnp.asarray(np.tile(w0, (2, 1))), jnp.asarray(buf_w),
        jnp.asarray(x_w))]
    return w0, x_w, buf_w, w_out, buf_out
