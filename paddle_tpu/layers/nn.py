"""Neural-network layers DSL (reference: ``python/paddle/fluid/layers/nn.py``,
12.4k LoC / 172 functions — built here op-by-op on the TPU op registry)."""

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer, NormalInitializer
from .. import core

__all__ = [
    "fc",
    "embedding",
    "conv2d",
    "depthwise_conv2d",
    "conv2d_transpose",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "dropout",
    "softmax",
    "log_softmax",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "smooth_l1",
    "huber_loss",
    "label_smooth",
    "mean",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "matmul",
    "mul",
    "fused_dropout_add_ln",
    "fused_multihead_attention",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "reshape",
    "transpose",
    "concat",
    "split",
    "squeeze",
    "unsqueeze",
    "flatten",
    "stack",
    "unstack",
    "expand",
    "slice",
    "gather",
    "gather_nd",
    "scatter",
    "one_hot",
    "topk",
    "argmax",
    "argmin",
    "argsort",
    "shape",
    "clip",
    "clip_by_norm",
    "l2_normalize",
    "relu",
    "prelu",
    "leaky_relu",
    "pad",
    "pad2d",
    "image_resize",
    "resize_bilinear",
    "resize_nearest",
    "where",
    "cond_not_supported",
    "lod_reset",
    "group_norm",
    "cos_sim",
    "unsqueeze",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully connected layer (reference nn.py fc): mul (+ sum over multiple
    inputs) + bias + activation.  Lowered as one jnp.matmul per input —
    MXU-shaped; bias/act fuse in XLA."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, p_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        param_shape = [
            int(np.prod([abs(d) for d in input_shape[num_flatten_dims:]]))
        ] + [size]
        w = helper.create_parameter(
            attr=p_attr, shape=param_shape, dtype=dtype, is_bias=False
        )
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [input_var], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="sum",
            inputs={"X": mul_results},
            outputs={"Out": [pre_bias]},
        )
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Embedding lookup (reference nn.py embedding → lookup_table op).
    is_sparse selects the reference's SelectedRows grad path; on TPU the
    grad is always XLA scatter-add, so the flag is accepted and ignored.

    is_distributed=True row-shards the table over the mesh's data axis
    when the program runs under ``CompiledProgram.with_data_parallel`` —
    the TPU-native replacement for the reference's parameter-server
    distributed lookup table (``transpiler/distribute_transpiler.py:
    353-376``, ``operators/distributed/parameter_prefetch.cc``): GSPMD
    partitions the lookup/scatter-grad with the id exchange over ICI
    instead of RPC remote_prefetch, and the optimizer state shards with
    the table."""
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=size, dtype=dtype, is_bias=False
    )
    if is_distributed:
        w._is_distributed = True
    tmp = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1 if padding_idx is None
        else padding_idx if padding_idx >= 0
        else size[0] + padding_idx
    )
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [tmp]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": padding_idx,
        },
    )
    return tmp


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return [int(v)] * n


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", **locals())
    dtype = input.dtype
    groups = groups or 1
    num_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=NormalInitializer(0.0, std),
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    op_type = (
        "depthwise_conv2d"
        if groups == num_channels and num_filters % num_channels == 0
        else "conv2d"
    )
    helper.append_op(
        type=op_type,
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
        },
    )
    # bias is per output CHANNEL: axis 1 for NCHW, last for NHWC (a
    # layout-blind axis-1 add would silently bias over H instead)
    if data_format == "NCHW":
        pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    else:
        nd = len(input.shape)
        pre_act = helper.append_bias_op(pre_bias, dim_start=nd - 1,
                                        dim_end=nd)
    return helper.append_activation(pre_act)


def depthwise_conv2d(input, num_filters, filter_size, **kwargs):
    groups = (input.shape[1]
              if kwargs.get("data_format", "NCHW") == "NCHW"
              else input.shape[-1])
    return conv2d(input, num_filters, filter_size, groups=groups,
                  **kwargs)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = input.dtype
    groups = groups or 1
    num_channels = input.shape[1]
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    if filter_size is None:
        raise ValueError("filter_size required (output_size inference TBD)")
    filter_size = _pair(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format="NCHW"):
    helper = LayerHelper("pool2d", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    helper = LayerHelper("batch_norm", **locals())
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    shape = [c]
    scale = helper.create_parameter(
        attr=helper.param_attr, shape=shape, dtype="float32",
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=shape, dtype="float32", is_bias=True
    )
    from ..param_attr import ParamAttr

    mean = helper.create_parameter(
        attr=ParamAttr(name=moving_mean_name, trainable=False),
        shape=shape, dtype="float32",
        default_initializer=ConstantInitializer(0.0),
    )
    variance = helper.create_parameter(
        attr=ParamAttr(name=moving_variance_name, trainable=False),
        shape=shape, dtype="float32",
        default_initializer=ConstantInitializer(1.0),
    )
    mean.stop_gradient = True
    variance.stop_gradient = True

    out = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference("float32", True)
    saved_var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(
        type="batch_norm",
        inputs={
            "X": [input], "Scale": [scale], "Bias": [bias],
            "Mean": [mean], "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", **locals())
    dtype = input.dtype
    input_shape = input.shape
    param_shape = [int(np.prod([abs(d) for d in input_shape[begin_norm_axis:]]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr, shape=param_shape, dtype="float32",
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=param_shape, dtype="float32",
            is_bias=True,
        )
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    """Group normalization (reference layers/nn.py:3487; kernel
    group_norm_op.cc) over the channel axis of an NCHW tensor."""
    if data_layout != "NCHW":
        raise ValueError("group_norm supports data_layout='NCHW' only, "
                         "got %r" % (data_layout,))
    helper = LayerHelper("group_norm", **locals())
    dtype = input.dtype
    c = int(input.shape[1])
    inputs = {"X": [input]}
    if param_attr is not False:
        scale = helper.create_parameter(
            attr=helper.param_attr, shape=[c], dtype="float32",
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [scale]
    if bias_attr is not False:
        bias = helper.create_parameter(
            attr=helper.bias_attr, shape=[c], dtype="float32", is_bias=True,
        )
        inputs["Bias"] = [bias]
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(
        type="group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"groups": int(groups), "epsilon": float(epsilon)},
    )
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("uint8", True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="softmax", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="log_softmax", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy", **locals())
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={
            "soft_label": soft_label,
            "ignore_index": ignore_index,
            "axis": axis,
        },
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="square_error_cost",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out]},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss", **locals())
    diff = helper.create_variable_for_type_inference(x.dtype, True)
    loss = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Diff": [diff], "Out": [loss]},
        attrs={"sigma": sigma if sigma is not None else 1.0},
    )
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss", **locals())
    residual = helper.create_variable_for_type_inference(input.dtype, True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out], "Residual": [residual]},
        attrs={"delta": delta},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(
        type="label_smooth", inputs=inputs, outputs={"Out": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is not None and not isinstance(dim, (list, tuple)):
        dim = [dim]
    helper.append_op(
        type=op_type,
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "dim": dim if dim is not None else [0],
            "keep_dim": keep_dim,
            "reduce_all": dim is None,
        },
    )
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={
            "transpose_X": transpose_x,
            "transpose_Y": transpose_y,
            "alpha": float(alpha),
        },
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={
            "x_num_col_dims": x_num_col_dims,
            "y_num_col_dims": y_num_col_dims,
        },
    )
    return out


def _elementwise(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type=op_type,
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op(
        type="reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"shape": [int(s) for s in shape]},
    )
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op(
        type="transpose2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": list(perm)},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", **locals())
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(
        type="concat",
        inputs={"X": list(input)},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", **locals())
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        attrs = {"num": num, "axis": dim, "sections": []}
    else:
        num = len(num_or_sections)
        attrs = {"num": 0, "axis": dim,
                 "sections": [int(s) for s in num_or_sections]}
    outs = [
        helper.create_variable_for_type_inference(input.dtype)
        for _ in range(num)
    ]
    helper.append_op(
        type="split", inputs={"X": [input]}, outputs={"Out": outs},
        attrs=attrs,
    )
    return outs


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        type="squeeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        type="unsqueeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op(
        type="flatten2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": axis},
    )
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack", **locals())
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(
        type="stack", inputs={"X": list(x)}, outputs={"Y": [out]},
        attrs={"axis": axis},
    )
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack", **locals())
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(num)]
    helper.append_op(
        type="unstack", inputs={"X": [x]}, outputs={"Y": outs},
        attrs={"axis": axis, "num": num},
    )
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"expand_times": [int(t) for t in expand_times]},
    )
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="slice", inputs={"Input": [input]}, outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": [int(s) for s in starts],
               "ends": [int(e) for e in ends]},
    )
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gather", inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gather_nd", inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot", **locals())
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="one_hot", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"depth": depth},
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", **locals())
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="top_k", inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": int(k)},
    )
    return values, indices


def argmax(x, axis=0):
    helper = LayerHelper("arg_max", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="arg_max", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="arg_min", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="argsort", inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis},
    )
    return out, ids


def shape(input):
    helper = LayerHelper("shape", **locals())
    out = helper.create_variable_for_type_inference("int32", True)
    helper.append_op(
        type="shape", inputs={"Input": [input]}, outputs={"Out": [out]}
    )
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"min": float(min), "max": float(max)},
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="clip_by_norm", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"max_norm": float(max_norm)},
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    from . import ops as _ops

    sq = elementwise_mul(x, x)
    s = reduce_sum(sq, dim=axis, keep_dim=True)
    norm = _ops.sqrt(elementwise_add(s, fill_like_scalar(s, epsilon)))
    return elementwise_div(x, norm)


def fill_like_scalar(ref, value):
    from .tensor import fill_constant

    return fill_constant([1], ref.dtype, value)


def relu(x, name=None):
    helper = LayerHelper("relu", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="relu", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", **locals())
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        attr=helper.param_attr, shape=alpha_shape, dtype="float32",
        is_bias=False, default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="prelu", inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]}, attrs={"mode": mode},
    )
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="leaky_relu", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"alpha": alpha},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pad2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "mode": mode,
               "pad_value": float(pad_value), "data_format": data_format},
    )
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    """Resize NCHW images (reference layers/nn.py:7483; interpolate_op.cc).

    TPU redesign: the output H/W must be static Python ints (XLA static
    shapes) — tensor-valued `out_shape`/`actual_shape` are rejected with
    a targeted error instead of the reference's runtime OutSize input.
    """
    resample = str(resample).upper()
    if resample not in ("BILINEAR", "NEAREST"):
        raise ValueError(
            "image_resize resample must be 'BILINEAR' or 'NEAREST', got %r"
            % (resample,))
    if actual_shape is not None or isinstance(out_shape, Variable):
        raise ValueError(
            "image_resize on TPU needs a static out_shape (list/tuple of "
            "ints); tensor-valued out_shape/actual_shape would make the "
            "compiled shape dynamic")
    h, w = int(input.shape[2]), int(input.shape[3])
    if out_shape is not None:
        if len(out_shape) != 2:
            raise ValueError("out_shape must be [out_h, out_w]")
        oh, ow = int(out_shape[0]), int(out_shape[1])
    elif scale is not None:
        oh, ow = int(h * float(scale)), int(w * float(scale))
    else:
        raise ValueError("one of out_shape and scale must be set")
    helper = LayerHelper("image_resize", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="bilinear_interp" if resample == "BILINEAR" else "nearest_interp",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": oh, "out_w": ow,
               "align_corners": bool(align_corners),
               "align_mode": int(align_mode)},
    )
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    """reference layers/nn.py:7706."""
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    """reference layers/nn.py:7811."""
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners)


def where(condition, x=None, y=None):
    helper = LayerHelper("where", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="where",
        inputs={"Condition": [condition], "X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def cond_not_supported(*a, **k):
    raise NotImplementedError


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    helper.append_op(
        type="lod_reset", inputs=inputs, outputs={"Out": [out]},
        attrs={"target_lod": target_lod or []},
    )
    return out


def cos_sim(X, Y):
    xn = l2_normalize(X, axis=-1)
    yn = l2_normalize(Y, axis=-1)
    return reduce_sum(elementwise_mul(xn, yn), dim=-1, keep_dim=True)


def fused_dropout_add_ln(x, residual, dropout_prob=0.0, epsilon=1e-5,
                         param_attr=None, bias_attr=None, name=None):
    """``layer_norm(residual + dropout(x))`` over the LAST axis as one
    fused op (Pallas kernel on TPU, XLA expression elsewhere) — the
    transformer encoder's inter-GEMM glue without the intermediate HBM
    round-trips.  Creates LN scale/bias parameters of shape [D] like
    ``layer_norm(begin_norm_axis=ndim-1)``."""
    helper = LayerHelper("fused_dropout_add_ln", **locals())
    d = x.shape[-1]
    # params match layer_norm's exactly (float32 + is_bias) so the two
    # graph forms stay checkpoint-compatible under the same names
    scale = helper.create_parameter(
        attr=helper.param_attr, shape=[d], dtype="float32",
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[d], dtype="float32",
        is_bias=True)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="fused_dropout_add_ln",
        inputs={"X": [x], "Residual": [residual], "Scale": [scale],
                "Bias": [bias]},
        outputs={"Out": [out]},
        attrs={"dropout_prob": float(dropout_prob),
               "epsilon": float(epsilon)},
    )
    return out


def fused_multihead_attention(q, k, v, bias=None, causal=False, scale=None,
                              dropout_rate=0.0, name=None):
    """Fused multi-head attention over [B, H, T, Dh] tensors; on TPU this
    is a single Pallas flash-attention kernel (O(T) memory), elsewhere XLA
    attention.  `bias` is an additive key bias ([B, Tk] or [B,1,1,Tk],
    e.g. a padding mask); no gradient flows to it.  dropout_rate applies
    attention-probability dropout INSIDE the kernel (train mode only) —
    the [B,H,T,T] mask never materializes in HBM."""
    helper = LayerHelper("fused_multihead_attention", **locals())
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["BiasQK"] = [bias]
    attrs = {"causal": bool(causal)}
    if dropout_rate:
        attrs["dropout_rate"] = float(dropout_rate)
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(
        type="fused_multihead_attention",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs=attrs,
    )
    return out


# Reference parity: the reference keeps all of these names in ONE
# layers/nn.py module, so `from paddle.fluid.layers.nn import X` works
# for every entry.  This repo splits the implementation across
# nn_extra/nn_extra2 for file size; re-exporting them here restores the
# single-module import surface (nn_extra* import nothing from this
# module, so the late import is cycle-free).
#
# CAUTION for future edits to THIS module: the star-imports below bind
# layer ops over the builtins `sum` and `hash` (reference nn exports
# both).  Globals resolve at CALL time, so code ANYWHERE in this module
# (before or after this point) must not call those builtins
# unqualified — use builtins.sum / builtins.hash.
from .nn_extra import *  # noqa: E402,F401,F403
from .nn_extra2 import *  # noqa: E402,F401,F403
from .nn_extra import __all__ as _extra_all
from .nn_extra2 import __all__ as _extra2_all

__all__ = list(__all__) + list(_extra_all) + list(_extra2_all)


def _reexport_reference_nn_names():
    """The reference nn.py also hosts the sequence/rnn/beam/unary-op
    layer names; pull EXACTLY the reference-nn names this repo homes
    elsewhere into this module so `from ...layers.nn import X` covers
    the full reference nn __all__ (169 names).  The list is curated —
    a blanket re-export of those modules' __all__ would also drag in
    names like `abs` that shadow builtins this module's own code uses."""
    import sys

    from . import beam, detection, ops, sequence

    # ONLY names absent after the nn_extra star-imports above (names
    # those already bind — selu, sum, rank, roi_pool, lstm, ... — are
    # deliberately not listed; the hasattr guard is belt-and-braces)
    wanted = [
        "sequence_pool", "sequence_softmax", "sequence_expand",
        "sequence_pad", "sequence_unpad", "sequence_first_step",
        "sequence_last_step", "sequence_slice", "sequence_mask",
        "sequence_enumerate", "sequence_concat", "sequence_reverse",
        "beam_search", "beam_search_decode",
        "dynamic_lstm", "dynamic_gru",
        "roi_align",
        "log", "pow", "scale", "sign", "elu", "relu6", "stanh",
        "hard_sigmoid", "swish", "brelu", "soft_relu",
        "logical_and", "logical_or", "logical_xor", "logical_not",
    ]
    here = sys.modules[__name__]
    for name in wanted:
        if hasattr(here, name):
            continue
        for mod in (beam, detection, ops, sequence):
            if hasattr(mod, name):
                setattr(here, name, getattr(mod, name))
                __all__.append(name)
                break


_reexport_reference_nn_names()
