"""Control-flow layers (reference:
``python/paddle/fluid/layers/control_flow.py``: While:630, StaticRNN:280,
Switch:1436, ConditionalBlock:1352 — each opens a sub-block).

TPU lowering: sub-blocks lower ONCE to pure jax functions run under
``lax.while_loop`` / ``lax.cond`` / ``lax.scan`` (ops/control_flow.py) —
compiled control flow, no per-iteration interpreter dispatch.  Loop-state
vars must be created BEFORE the loop and assigned inside it (the same
discipline the reference requires); shapes must be loop-invariant (XLA
static shapes).
"""

from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from .. import core
from ..ops.control_flow import ARRAY_CAPACITY_ATTR, DEFAULT_ARRAY_CAPACITY
from . import tensor as _tensor

__all__ = [
    "While",
    "StaticRNN",
    "Switch",
    "ConditionalBlock",
    "recompute",
    "IfElse",
    "DynamicRNN",
    "increment",
    "array_write",
    "array_read",
    "array_length",
    "create_array",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal",
    "not_equal",
    "cond",
    "Print",
    "is_empty",
    "reorder_lod_tensor_by_rank",
]


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", **locals())
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="increment", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type, **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool", True)
    helper.append_op(
        type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]},
    )
    return cond


def less_than(x, y, force_cpu=None, cond=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


# ---------------------------------------------------------------------------
# LoDTensorArray (fixed-capacity device buffer — see ops/control_flow.py)
# ---------------------------------------------------------------------------

def create_array(dtype, capacity=DEFAULT_ARRAY_CAPACITY):
    helper = LayerHelper("array")
    var = helper.main_program.current_block().create_var(
        name=helper.name + ".out",
        dtype=dtype,
        type=core.VarDesc.VarType.LOD_TENSOR_ARRAY,
    )
    # capacity rides on the var so every subsequent array_write allocates
    # the same fixed-size device buffer
    var._tensor_array_capacity = int(capacity)
    return var


def array_write(x, i, array=None, capacity=None):
    helper = LayerHelper("array_write", **locals())
    fresh = array is None
    if capacity is None:
        capacity = getattr(array, "_tensor_array_capacity",
                           DEFAULT_ARRAY_CAPACITY) if array is not None \
            else DEFAULT_ARRAY_CAPACITY
    if fresh:
        array = create_array(x.dtype, capacity)
    # the first write to an array can't read a prior buffer value; a write
    # that may re-run (e.g. inside a While body) must read it so the value
    # is loop-carried
    first_write = not getattr(array, "_tensor_array_written", False)
    inputs = {"X": [x], "I": [i]}
    if not first_write:
        inputs["Array"] = [array]
    array._tensor_array_written = True
    helper.append_op(
        type="write_to_array",
        inputs=inputs,
        outputs={"Out": [array]},
        attrs={ARRAY_CAPACITY_ATTR: int(capacity)},
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", **locals())
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(
        type="read_from_array",
        inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length", **locals())
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op(
        type="lod_array_length", inputs={"X": [array]},
        outputs={"Out": [out]},
    )
    return out


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------

class BlockGuard:
    def __init__(self, program):
        self.program = program

    def __enter__(self):
        self.program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.program._rollback()
        return exc_type is None


class WhileGuard(BlockGuard):
    def __init__(self, while_op):
        super().__init__(while_op.helper.main_program)
        self.while_op = while_op

    def __enter__(self):
        self.sub_block = self.program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.program._rollback()
        self.while_op._complete(self.sub_block)
        return True


def _has_value_before(block, name):
    """Graph-time check: will `name` hold a value at this point of the
    block (written earlier, fed, or persistable)?  Used to decide which
    loop-state vars get a pre-loop snapshot for while_grad."""
    b = block
    while b is not None:
        for op in b.ops:
            if name in op.output_arg_names:
                return True
        v = b.vars.get(name)
        if v is not None and (v.persistable or v.is_data):
            return True
        b = (b.program.block(b.parent_idx)
             if getattr(b, "parent_idx", -1) not in (-1, None) else None)
    return False


class While:
    """``with While(cond).block(): ...`` — the condition var must be
    reassigned inside the block (reference control_flow.py:630).

    TPU-native extension: pass ``max_trip_count=N`` to make the loop
    differentiable — backward lowers the loop to a lax.scan over N steps
    with an active mask (XLA cannot transpose an unbounded while_loop).
    The forward still runs as a true ``lax.while_loop`` (early exit)."""

    def __init__(self, cond, is_test=False, name=None, max_trip_count=None):
        self.helper = LayerHelper("while", name=name)
        if cond.dtype != "bool":
            raise TypeError("While condition must be a bool Variable")
        self.cond_var = cond
        self.max_trip_count = max_trip_count

    def block(self):
        return WhileGuard(self)

    def _complete(self, sub_block):
        from .. import unique_name

        parent = self.helper.main_program.current_block()
        # external reads = X; writes that exist outside = Out (loop state)
        written = set()
        reads = []
        for op in sub_block.ops:
            for n in op.input_arg_names:
                if n not in written and n not in reads:
                    reads.append(n)
            written.update(op.output_arg_names)
        x_names = [
            n for n in reads
            if parent._find_var_recursive(n) is not None
        ]
        out_names = [
            n for n in written
            if parent._find_var_recursive(n) is not None
        ]
        # snapshot pre-loop values of loop-state vars (incl. the condition)
        # so while_grad can rebuild the loop from its initial state; unused
        # snapshots are dead code XLA eliminates
        snap_vars, snap_pres = [], []
        for n in sorted(set(out_names) | {self.cond_var.name}):
            if not _has_value_before(parent, n):
                continue
            v = parent._find_var_recursive(n)
            pre = parent.create_var(
                name=unique_name.generate(n + "@WHILE_PRE"),
                shape=v.shape, dtype=v.dtype,
            )
            parent.append_op(
                type="assign", inputs={"X": [n]}, outputs={"Out": [pre.name]}
            )
            snap_vars.append(n)
            snap_pres.append(pre.name)
        step_scopes = parent.create_var(
            name=self.helper.name + ".step_scopes",
            type=core.VarDesc.VarType.STEP_SCOPES,
        )
        parent.append_op(
            type="while",
            inputs={"X": x_names, "Condition": [self.cond_var]},
            outputs={"Out": out_names, "StepScopes": [step_scopes]},
            attrs={
                "sub_block": sub_block.idx,
                "is_test": False,
                "max_trip_count": int(self.max_trip_count or 0),
                "snapshot_vars": snap_vars,
                "snapshot_pres": snap_pres,
            },
        )


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Functional two-branch conditional: both branches lower through
    ConditionalBlock → lax.cond, merging into shared outer output vars
    (zero-initialized, then assigned by whichever branch runs)."""
    from .. import unique_name
    from . import tensor as layers_tensor

    helper = LayerHelper("cond", name=name)
    parent = helper.main_program.current_block()
    out_vars = []

    def capture(rets):
        if rets is None:
            return
        rets_t = list(rets) if isinstance(rets, (list, tuple)) else [rets]
        if not out_vars:
            for r in rets_t:
                if r.shape is None or any(d < 0 for d in r.shape):
                    raise ValueError(
                        "cond() branch outputs need static shapes on TPU "
                        "(got %s for %s)" % (r.shape, r.name)
                    )
                ov = parent.create_var(
                    name=unique_name.generate("cond.out"),
                    shape=r.shape, dtype=r.dtype,
                )
                parent.append_op(
                    type="fill_constant",
                    outputs={"Out": [ov]},
                    attrs={"shape": list(r.shape), "dtype": r.dtype,
                           "value": 0.0},
                )
                out_vars.append(ov)
        cur = helper.main_program.current_block()
        for r, ov in zip(rets_t, out_vars):
            cur.append_op(
                type="assign", inputs={"X": [r]}, outputs={"Out": [ov]}
            )

    cb = ConditionalBlock([pred])
    with cb.block():
        capture(true_fn() if true_fn is not None else None)
    if false_fn is not None:
        notp = _logical_not(pred)
        cb2 = ConditionalBlock([notp])
        with cb2.block():
            capture(false_fn())
    if not out_vars:
        return None
    return out_vars[0] if len(out_vars) == 1 else out_vars


class ConditionalBlock:
    """Run a sub-block iff cond is true (reference control_flow.py:1352);
    vars assigned inside keep their prior value when cond is false."""

    def __init__(self, inputs, is_scalar_condition=True, name=None):
        self.helper = LayerHelper("conditional_block", name=name)
        self.inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]

    def block(self):
        return ConditionalBlockGuard(self)

    def _complete(self, sub_block):
        parent = self.helper.main_program.current_block()
        written = set()
        for op in sub_block.ops:
            written.update(op.output_arg_names)
        out_names = [
            n for n in written
            if parent._find_var_recursive(n) is not None
        ]
        scope_var = parent.create_var(
            name=self.helper.name + ".scope",
            type=core.VarDesc.VarType.STEP_SCOPES,
        )
        parent.append_op(
            type="conditional_block",
            inputs={"Cond": [self.inputs[0]]},
            outputs={"Out": out_names, "Scope": [scope_var]},
            attrs={"sub_block": sub_block.idx},
        )


def make_recompute_region_op_spec(parent, sub_block, scope_name):
    """The ``recompute_block`` op contract, shared by
    ``fluid.layers.recompute()`` and
    ``optimizer.rewrite_program_recompute`` (one definition of the
    Captured/Out/Scope plumbing): outputs = every name the region
    writes; Captured = the region's closure reads that resolve in the
    parent (they MUST be formal inputs — backward's op-path pruning and
    the executor's external-read analysis walk input edges)."""
    from ..ops.control_flow import sub_block_external_reads

    written = []
    for op in sub_block.ops:
        for n in op.output_arg_names:
            if n and n not in written:
                written.append(n)
    captured = [
        n for n in sub_block_external_reads(sub_block)
        if parent._find_var_recursive(n) is not None
    ]
    scope_var = parent.create_var(
        name=scope_name, type=core.VarDesc.VarType.STEP_SCOPES)
    return dict(
        type="recompute_block",
        inputs={"Captured": captured},
        outputs={"Out": written, "Scope": [scope_var.name]},
        attrs={"sub_block": sub_block.idx},
    )


class _RecomputeGuard(BlockGuard):
    """``with fluid.layers.recompute():`` — activation rematerialization
    (SURVEY §7g "remat"; beyond the v1.5 reference, which has no
    recompute; later Paddle added RecomputeOptimizer).

    Ops built inside the region lower as ONE ``recompute_block`` op; its
    grad op re-runs the region's forward from optimization-barriered
    inputs (jax.checkpoint's own mechanism) instead of keeping the
    intermediate activations live — peak memory for the region drops to
    its inputs+outputs at the cost of one extra forward, on backends
    whose scheduler honors the barrier (TPU; the XLA CPU pipeline CSE's
    remat away for native jax.checkpoint too).  Gradients are
    numerically identical (dropout keys are per-op deterministic, so
    the recomputed masks match)."""

    def __init__(self, name=None):
        from ..framework import default_main_program

        super().__init__(default_main_program())
        self.helper = LayerHelper("recompute_block", name=name)

    def __enter__(self):
        self.sub_block = self.program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            # always leave the block stack sane — a caught exception must
            # not strand subsequent layers in the orphaned sub-block
            self.program._rollback()
            return False
        self.program._rollback()
        parent = self.program.current_block()
        # vars created inside the region must stay referable by later
        # layers: promote them to the parent block (activation tmp vars
        # only — params are persistables in the global block already)
        for name, var in self.sub_block.vars.items():
            if parent._find_var_recursive(name) is None:
                parent.vars[name] = var
        spec = make_recompute_region_op_spec(
            parent, self.sub_block, self.helper.name + ".scope")
        parent.append_op(**spec)
        return True


def recompute(name=None):
    """Context manager: ops built inside are rematerialized in backward
    (region runs under jax.checkpoint).  Usage::

        with fluid.layers.recompute():
            h = fluid.layers.fc(h, size=1024, act="relu")
            h = fluid.layers.fc(h, size=1024, act="relu")
    """
    return _RecomputeGuard(name=name)


class ConditionalBlockGuard(BlockGuard):
    def __init__(self, cb):
        super().__init__(cb.helper.main_program)
        self.cb = cb

    def __enter__(self):
        self.sub_block = self.program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.program._rollback()
        self.cb._complete(self.sub_block)
        return True


class Switch:
    """case/default chain built from ConditionalBlocks (reference
    control_flow.py:1436)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conditions = []
        self.inside_scope = False

    def case(self, condition):
        from . import nn as _nn

        # condition AND not(any previous condition)
        cur = condition
        for prev in self.pre_not_conditions:
            cur = _logical_and(cur, prev)
        self.pre_not_conditions.append(_logical_not(condition))
        return ConditionalBlock([cur]).block()

    def default(self):
        cur = self.pre_not_conditions[0]
        for prev in self.pre_not_conditions[1:]:
            cur = _logical_and(cur, prev)
        return ConditionalBlock([cur]).block()

    def __enter__(self):
        self.inside_scope = True
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.inside_scope = False
        return exc_type is None


def _logical_and(x, y):
    from .ops import logical_and

    return logical_and(x, y)


def _logical_not(x):
    from .ops import logical_not

    return logical_not(x)


class IfElseBlockGuard:
    def __init__(self, is_true, ie):
        self.is_true = is_true
        self.ie = ie

    def __enter__(self):
        self.ie.status = (IfElse.IN_IF_ELSE_TRUE_BLOCKS if self.is_true
                          else IfElse.IN_IF_ELSE_FALSE_BLOCKS)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        if not self.ie.output_table[1 if self.is_true else 0]:
            raise ValueError("Must set output inside block")
        self.ie.status = IfElse.OUT_IF_ELSE_BLOCKS
        return True


class IfElse:
    """Per-row two-branch control flow (reference control_flow.py:1564:
    split_lod_tensor partitions rows by a [B,1] bool mask, each branch
    runs on its sub-batch, merge_lod_tensor re-interleaves).

    TPU-static redesign: ragged row partitions are not expressible under
    XLA static shapes, so BOTH branches compute on the full batch and the
    merge selects per row (``merge_lod_tensor`` → jnp.where) — identical
    results for row-wise computations, with the reference's op names kept
    in the program for parity.  Usage::

        ie = fluid.layers.IfElse(cond)        # cond: [B, 1] bool
        with ie.true_block():
            x_t = ie.input(x)
            ie.output(some_layers(x_t))
        with ie.false_block():
            x_f = ie.input(x)
            ie.output(other_layers(x_f))
        merged, = ie()
    """

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        if not isinstance(cond, Variable):
            raise TypeError("cond must be a Variable")
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        self.input_table = {}
        self.output_table = ([], [])  # (false_outs, true_outs)

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("input() must be called inside a block")
        if id(x) not in self.input_table:
            # program parity: record the split op; both halves carry the
            # full batch (see ops/control_flow.py split_lod_tensor)
            block = self.helper.main_program.current_block()
            out_true = block.create_var(
                name=self.helper.name + ".in_true_%d" % len(self.input_table),
                shape=x.shape, dtype=x.dtype)
            out_false = block.create_var(
                name=self.helper.name + ".in_false_%d"
                % len(self.input_table),
                shape=x.shape, dtype=x.dtype)
            block.append_op(
                type="split_lod_tensor",
                inputs={"X": [x], "Mask": [self.cond]},
                outputs={"OutTrue": [out_true], "OutFalse": [out_false]},
                attrs={"level": 0},
            )
            self.input_table[id(x)] = (out_true, out_false)
        out_true, out_false = self.input_table[id(x)]
        return (out_true if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
                else out_false)

    def true_block(self):
        return IfElseBlockGuard(True, self)

    def false_block(self):
        return IfElseBlockGuard(False, self)

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("output() can only be invoked inside a block")
        table = self.output_table[
            1 if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS else 0]
        table.extend(outs)

    def __call__(self):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("IfElse::__call__ must be out of sub-blocks")
        false_outs, true_outs = self.output_table
        if len(false_outs) != len(true_outs):
            raise ValueError(
                "true_block and false_block must set the same number of "
                "outputs (%d vs %d)" % (len(true_outs), len(false_outs)))
        block = self.helper.main_program.current_block()
        merged = []
        for i, (t, f) in enumerate(zip(true_outs, false_outs)):
            out = block.create_var(
                name=self.helper.name + ".out_%d" % i,
                shape=t.shape, dtype=t.dtype)
            block.append_op(
                type="merge_lod_tensor",
                inputs={"InTrue": [t], "InFalse": [f],
                        "Mask": [self.cond], "X": [t]},
                outputs={"Out": [out]},
                attrs={"level": 0},
            )
            merged.append(out)
        return merged


# ---------------------------------------------------------------------------
# StaticRNN
# ---------------------------------------------------------------------------

class StaticRNNGuard(BlockGuard):
    def __init__(self, rnn):
        super().__init__(rnn.helper.main_program)
        self.rnn = rnn

    def __enter__(self):
        self.sub_block = self.program._create_block()
        self.rnn._sub_block = self.sub_block
        self.rnn.status = StaticRNN.IN_RNN_BLOCK
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.program._rollback()
        self.rnn.status = StaticRNN.AFTER_RNN_BLOCK
        self.rnn._complete()
        return True


class StaticRNN:
    """Unrolled-by-scan RNN over time-major [T, B, ...] sequences
    (reference control_flow.py:280 → recurrent_op.cc)."""

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self._sub_block = None
        self.seq_inputs = []     # outer [T,B,...] vars
        self.step_input_vars = []  # per-step sub-block vars
        self.memories = []       # (pre_state_var, init_var)
        self.mem_updates = {}    # pre_state name -> new value name
        self.step_outputs = []   # per-step output vars
        self.outputs = []        # outer stacked outputs

    def step(self):
        return StaticRNNGuard(self)

    def _assert_in_rnn_block(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise RuntimeError("%s() can only be called inside rnn.step()"
                               % method)

    def step_input(self, x):
        self._assert_in_rnn_block("step_input")
        self.seq_inputs.append(x)
        sv = self._sub_block.create_var(
            name=self.helper.name + ".step_in_%d" % len(self.step_input_vars),
            shape=x.shape[1:] if x.shape else None,
            dtype=x.dtype,
        )
        self.step_input_vars.append(sv)
        return sv

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._assert_in_rnn_block("memory")
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape & batch_ref)")
            # the init must exist BEFORE the recurrent op runs, so its
            # fill op goes into the PARENT block, sized from the outer
            # sequence var (batch dim 1 of the time-major [T,B,...] input)
            ref = batch_ref
            for sv, seq in zip(self.step_input_vars, self.seq_inputs):
                if ref is sv or ref.name == sv.name:
                    ref = seq
                    break
            else:
                raise ValueError(
                    "batch_ref must be one of this RNN's step_input vars"
                )
            parent = self.helper.main_program.block(
                self._sub_block.parent_idx
            )
            init = parent.create_var(
                name=self.helper.name + ".mem_init_%d" % len(self.memories),
                shape=(-1,) + tuple(shape),
                dtype="float32",
            )
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [ref]},
                outputs={"Out": [init]},
                attrs={
                    "shape": [0] + [int(s) for s in shape],
                    "dtype": "float32",
                    "value": float(init_value),
                    "input_dim_idx": 1,  # batch dim of [T,B,...]
                    "output_dim_idx": 0,
                },
            )
        pre = self._sub_block.create_var(
            name=self.helper.name + ".mem_%d" % len(self.memories),
            shape=init.shape, dtype=init.dtype,
        )
        self.memories.append((pre, init))
        return pre

    def update_memory(self, mem, var):
        self._assert_in_rnn_block("update_memory")
        self.mem_updates[mem.name] = var.name

    def step_output(self, o):
        self._assert_in_rnn_block("step_output")
        self.step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self, *args):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise RuntimeError("RNN output requested before step() closed")
        if len(self.outputs) == 1:
            return self.outputs[0]
        return self.outputs

    def _complete(self):
        parent = self.helper.main_program.current_block()
        out_vars = []
        for i, so in enumerate(self.step_outputs):
            T = self.seq_inputs[0].shape[0] if self.seq_inputs else -1
            ov = parent.create_var(
                name=self.helper.name + ".out_%d" % i,
                shape=(T,) + tuple(so.shape or ()),
                dtype=so.dtype,
            )
            out_vars.append(ov)
        self.outputs = out_vars
        final_states = []
        state_out_names = []
        for pre, init in self.memories:
            state_out_names.append(self.mem_updates.get(pre.name, pre.name))
        parent.append_op(
            type="recurrent",
            inputs={
                "inputs": [v.name for v in self.seq_inputs],
                "initial_states": [init.name for _, init in self.memories],
            },
            outputs={
                "outputs": [v.name for v in out_vars],
                "final_states": final_states,
            },
            attrs={
                "sub_block": self._sub_block.idx,
                "step_input_names": [v.name for v in self.step_input_vars],
                "state_names": [pre.name for pre, _ in self.memories],
                "state_out_names": state_out_names,
                "step_output_names": [v.name for v in self.step_outputs],
            },
        )


class DynamicRNNGuard(BlockGuard):
    def __init__(self, rnn):
        super().__init__(rnn.helper.main_program)
        self.rnn = rnn

    def __enter__(self):
        self.sub_block = self.program._create_block()
        self.rnn._sub_block = self.sub_block
        self.rnn.status = DynamicRNN.IN_RNN
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.program._rollback()
        self.rnn.status = DynamicRNN.AFTER_RNN
        self.rnn._complete()
        return True


class DynamicRNN:
    """Variable-length RNN over PADDED batch-major sequences (reference
    ``python/paddle/fluid/layers/control_flow.py:1700``).

    The reference walks ragged LoD batches with a lod_rank_table that
    reorders and shrinks the batch per step; under XLA's static shapes the
    TPU-native equivalent is a masked ``lax.scan``: sequences are padded to
    [B, T, ...], a `lengths` tensor [B] marks the real extents, state
    updates are masked with ``t < length`` (rows past their length carry
    the previous state), and padded step outputs are zeroed.

    Usage::

        drnn = DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x, lengths=seq_len)   # x: [B, T, D]
            h_prev = drnn.memory(shape=[H], value=0.0)
            h = some_layers(x_t, h_prev)
            drnn.update_memory(h_prev, h)
            drnn.output(h)
        out = drnn()                                    # [B, T, H]
    """

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self._sub_block = None
        self.seq_inputs = []
        self.step_input_vars = []
        self.lengths = None
        self.memories = []
        self.mem_updates = {}
        self.step_outputs = []
        self.outputs = []

    def block(self):
        return DynamicRNNGuard(self)

    def _assert_in_rnn_block(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise RuntimeError(
                "%s() can only be called inside drnn.block()" % method
            )

    def step_input(self, x, level=0, lengths=None):
        """Declare a [B, T, ...] padded sequence input; returns the per-step
        [B, ...] view.  `lengths` ([B] int tensor) must accompany the first
        step_input (it replaces the reference's LoD offsets)."""
        self._assert_in_rnn_block("step_input")
        if lengths is not None:
            self.lengths = lengths
        if self.lengths is None:
            raise ValueError(
                "DynamicRNN.step_input needs a `lengths` tensor with the "
                "first sequence input (padded batches carry explicit "
                "lengths instead of LoD)"
            )
        self.seq_inputs.append(x)
        shape = None
        if x.shape is not None:
            shape = (x.shape[0],) + tuple(x.shape[2:])
        sv = self._sub_block.create_var(
            name=self.helper.name + ".step_in_%d" % len(self.step_input_vars),
            shape=shape,
            dtype=x.dtype,
        )
        self.step_input_vars.append(sv)
        return sv

    def static_input(self, x):
        """A non-sequence var visible unchanged at every step (reference
        static_input reorders by rank table; the masked scan needs no
        reorder, so this is the identity — the var is closure-captured)."""
        self._assert_in_rnn_block("static_input")
        return x

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        self._assert_in_rnn_block("memory")
        if init is None:
            if shape is None or not self.seq_inputs:
                raise ValueError(
                    "memory needs init=, or shape= after a step_input"
                )
            ref = self.seq_inputs[0]
            parent = self.helper.main_program.block(
                self._sub_block.parent_idx
            )
            init = parent.create_var(
                name=self.helper.name + ".mem_init_%d" % len(self.memories),
                shape=(-1,) + tuple(shape),
                dtype=dtype,
            )
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [ref]},
                outputs={"Out": [init]},
                attrs={
                    "shape": [0] + [int(s) for s in shape],
                    "dtype": dtype,
                    "value": float(value),
                    "input_dim_idx": 0,  # batch dim of [B,T,...]
                    "output_dim_idx": 0,
                },
            )
        pre = self._sub_block.create_var(
            name=self.helper.name + ".mem_%d" % len(self.memories),
            shape=init.shape, dtype=init.dtype,
        )
        self.memories.append((pre, init))
        return pre

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn_block("update_memory")
        self.mem_updates[ex_mem.name] = new_mem.name

    def output(self, *outputs):
        self._assert_in_rnn_block("output")
        for o in outputs:
            self.step_outputs.append(o)

    def __call__(self, *args):
        if self.status != DynamicRNN.AFTER_RNN:
            raise RuntimeError(
                "DynamicRNN output requested before block() closed"
            )
        if len(self.outputs) == 1:
            return self.outputs[0]
        return self.outputs

    def _complete(self):
        parent = self.helper.main_program.current_block()
        B = self.seq_inputs[0].shape[0] if self.seq_inputs[0].shape else -1
        T = self.seq_inputs[0].shape[1] if self.seq_inputs[0].shape else -1
        out_vars = []
        for i, so in enumerate(self.step_outputs):
            ov = parent.create_var(
                name=self.helper.name + ".out_%d" % i,
                shape=(B, T) + tuple((so.shape or ())[1:]),
                dtype=so.dtype,
            )
            out_vars.append(ov)
        self.outputs = out_vars
        state_out_names = [
            self.mem_updates.get(pre.name, pre.name)
            for pre, _ in self.memories
        ]
        parent.append_op(
            type="recurrent",
            inputs={
                "inputs": [v.name for v in self.seq_inputs],
                "initial_states": [init.name for _, init in self.memories],
                "sequence_length": [self.lengths.name],
            },
            outputs={
                "outputs": [v.name for v in out_vars],
                "final_states": [],
            },
            attrs={
                "sub_block": self._sub_block.idx,
                "time_major": False,
                "step_input_names": [v.name for v in self.step_input_vars],
                "state_names": [pre.name for pre, _ in self.memories],
                "state_out_names": state_out_names,
                "step_output_names": [v.name for v in self.step_outputs],
            },
        )


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """reference control_flow.py Print → print op (jax.debug.print
    under jit)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"message": message or ""},
    )
    return out


def is_empty(x, cond=None):
    """reference control_flow.py is_empty → is_empty op."""
    helper = LayerHelper("is_empty")
    out = cond or helper.create_variable_for_type_inference("bool", True)
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """reference control_flow.py reorder_lod_tensor_by_rank op."""
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reorder_lod_tensor_by_rank",
        inputs={"X": [x], "RankTable": [rank_table]},
        outputs={"Out": [out]},
    )
    return out
