"""Control-flow layers (reference:
``python/paddle/fluid/layers/control_flow.py``: While:630, StaticRNN:280,
DynamicRNN:1700, IfElse:1564, Switch:1436 — each opens a sub-block).

TPU lowering: sub-blocks lower to ``lax.while_loop`` / ``lax.cond`` /
``lax.scan`` bodies (compiler-friendly control flow, no per-iteration host
dispatch).  The While/StaticRNN surface lands with the sequence batch
(stage 7 of SURVEY.md §7); array ops used by beam-search decoders are here.
"""

from ..framework import Variable
from ..layer_helper import LayerHelper
from . import tensor as _tensor

__all__ = [
    "increment",
    "array_write",
    "array_read",
    "array_length",
    "less_than",
    "equal",
    "not_equal",
    "greater_than",
    "While",
    "StaticRNN",
    "Switch",
    "IfElse",
    "DynamicRNN",
]


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", **locals())
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="increment", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type, **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool", True)
    helper.append_op(
        type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]},
    )
    return cond


def less_than(x, y, force_cpu=None, cond=None):
    return _compare("less_than", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def array_write(x, i, array=None):
    raise NotImplementedError(
        "LoDTensorArray ops land with the sequence/control-flow batch"
    )


def array_read(array, i):
    raise NotImplementedError(
        "LoDTensorArray ops land with the sequence/control-flow batch"
    )


def array_length(array):
    raise NotImplementedError(
        "LoDTensorArray ops land with the sequence/control-flow batch"
    )


class While:
    def __init__(self, cond, is_test=False, name=None):
        raise NotImplementedError(
            "While lowers to lax.while_loop — lands with stage 7 "
            "(control flow + sequences)"
        )


class StaticRNN:
    def __init__(self, name=None):
        raise NotImplementedError(
            "StaticRNN lowers to lax.scan — lands with stage 7"
        )


class Switch:
    def __init__(self, name=None):
        raise NotImplementedError("Switch lands with stage 7")


class IfElse:
    def __init__(self, cond, name=None):
        raise NotImplementedError(
            "IfElse lowers to lax.cond — lands with stage 7"
        )


class DynamicRNN:
    def __init__(self, name=None):
        raise NotImplementedError(
            "DynamicRNN maps to a masked lax.scan over padded+bucketed "
            "batches — lands with stage 7"
        )
